"""Plan sanity checking + compile-churn static analysis.

Analogue of Trino's sanity/PlanSanityChecker (ValidateDependenciesChecker,
TypeValidator, NoDuplicatePlanNodeIdsChecker, the AddExchanges
partitioning checks) and sanity/PlanDeterminismChecker, run over the
logical plan after optimizer passes and over the fragmented plan after
sql/fragmenter.py. A rule that mis-shifts an InputRef, drops a tstz
canonicalization, or desynchronizes exchange hash keys fails HERE with
the checker, node path, and last-applied rule named — instead of
surfacing as a wrong answer or a shape error deep in exec/.

The same plan walker doubles as a compile-churn static analyzer
(`shape_census`): under the static-shape discipline every operator
compiles one XLA program per distinct (operator, padded capacity class,
dtype signature) it sees (block.bucket_capacity rounds row counts to
powers of two precisely to keep this set small). The census enumerates
the classes a plan will request — including the retry-variant classes a
dynamic filter introduces when pruning changes probe capacities across
attempts — so EXPLAIN ANALYZE can print `expected_xla_lowerings` per
fragment and warn when a plan's class count exceeds the session
threshold (the measurable target for ROADMAP's shape-stabilization
work).

Checker vocabulary:
  refs           InputRef indices in bounds; node arity/schema widths
  types          expression dtypes recomputed bottom-up match Field dtypes
  structure      no duplicate node objects, acyclic, no leaked GroupRef /
                 ExchangeNode post-fragmentation, RemoteSourceNodes
                 reference existing fragments with schema agreement
  exchange_keys  repartition keys hash identically on both sides (count,
                 dtype, tstz keys zone-mask-canonicalized `$utc`)
  determinism    planning the same AST twice yields byte-identical
                 explain_text (check_plan_determinism)
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from trino_tpu import types as T
from trino_tpu.expr import ir
from trino_tpu.sql import plan as P


class PlanValidationError(RuntimeError):
    """Typed validation failure: which checker, where in the tree, and —
    when threaded through optimizer.Context — the last-applied rule."""

    def __init__(
        self,
        checker: str,
        node_path: str,
        message: str,
        rule: Optional[str] = None,
        stage: Optional[str] = None,
    ):
        self.checker = checker
        self.node_path = node_path
        self.rule = rule
        self.stage = stage
        where = f"[{checker}] at {node_path}"
        if stage:
            where += f" (stage={stage})"
        if rule:
            where += f" (last rule={rule})"
        super().__init__(f"{where}: {message}")


@dataclasses.dataclass(frozen=True)
class Violation:
    checker: str
    node_path: str
    message: str


# -- walking ------------------------------------------------------------------


def _child_tag(node: P.PlanNode, i: int) -> str:
    if isinstance(node, P.JoinNode):
        return ("left", "right")[i]
    if isinstance(node, P.UnionAllNode):
        return str(i)
    return ""


def _walk(node: P.PlanNode, path: str = ""):
    """Yield (path, node) pre-order; paths look like
    Output/Join[left]/Scan."""
    name = type(node).__name__.replace("Node", "")
    here = f"{path}/{name}" if path else name
    yield here, node
    for i, c in enumerate(node.children()):
        tag = _child_tag(node, i)
        yield from _walk(c, here + (f"[{tag}]" if tag else ""))


def _expr_walk(e: ir.Expr):
    yield e
    for c in e.children():
        yield from _expr_walk(c)


def _node_exprs(node: P.PlanNode) -> List[Tuple[str, ir.Expr, Tuple[P.Field, ...]]]:
    """(label, expr, input schema) triples for every expression a node
    carries. The input schema is what the expr's InputRefs index."""
    out: List[Tuple[str, ir.Expr, Tuple[P.Field, ...]]] = []
    if isinstance(node, P.FilterNode):
        out.append(("predicate", node.predicate, node.child.fields))
    elif isinstance(node, P.ProjectNode):
        for i, e in enumerate(node.exprs):
            out.append((f"exprs[{i}]", e, node.child.fields))
    elif isinstance(node, P.JoinNode) and node.residual is not None:
        out.append(
            ("residual", node.residual, node.left.fields + node.right.fields)
        )
    elif isinstance(node, P.MatchRecognizeNode):
        ext = node.child.fields + tuple(
            node.child.fields[ch] for ch, _ in node.shifts
        )
        for var, pred in node.defines:
            out.append((f"define[{var}]", pred, ext))
    return out


def _expected_width(node: P.PlanNode) -> Optional[int]:
    """Output width implied by the node's own shape, or None when the
    fields tuple is the only source of truth."""
    if isinstance(node, P.ScanNode):
        return len(node.columns)
    if isinstance(node, P.ProjectNode):
        return len(node.exprs)
    if isinstance(node, P.AggregateNode):
        k = len(node.group_channels)
        if node.step == "partial":
            return k + 2 * len(node.aggs)
        return k + len(node.aggs)
    if isinstance(node, P.JoinNode):
        nl = len(node.left.fields)
        if node.kind in ("semi", "anti"):
            return nl
        if node.kind in ("mark", "mark_exists"):
            return nl + 1
        return nl + len(node.right.fields)
    if isinstance(node, P.WindowNode):
        return len(node.child.fields) + len(node.functions)
    if isinstance(node, P.UnnestNode):
        return (
            len(node.child.fields)
            + len(node.array_channels)
            + (1 if node.ordinality else 0)
        )
    if isinstance(node, P.MatchRecognizeNode):
        return len(node.partition_channels) + len(node.measures)
    if isinstance(
        node,
        (P.FilterNode, P.SortNode, P.TopNNode, P.LimitNode,
         P.EnforceSingleRowNode, P.OutputNode, P.ExchangeNode),
    ):
        return len(node.children()[0].fields)
    return None


def _channel_lists(node: P.PlanNode) -> List[Tuple[str, Sequence[int], int]]:
    """(label, channels, input width) for every plain channel list a
    node carries."""
    out: List[Tuple[str, Sequence[int], int]] = []
    if isinstance(node, P.AggregateNode):
        w = len(node.child.fields)
        out.append(("group_channels", node.group_channels, w))
        for i, a in enumerate(node.aggs):
            chans = [
                c for c in (a.arg_channel, a.arg2_channel, a.arg3_channel)
                if c is not None
            ]
            out.append((f"aggs[{i}]", chans, w))
    elif isinstance(node, P.JoinNode):
        out.append(("left_keys", node.left_keys, len(node.left.fields)))
        out.append(("right_keys", node.right_keys, len(node.right.fields)))
    elif isinstance(node, P.WindowNode):
        w = len(node.child.fields)
        out.append(("partition_channels", node.partition_channels, w))
        out.append(("order_keys", [k.channel for k in node.order_keys], w))
        for i, f in enumerate(node.functions):
            if f.arg_channel is not None:
                out.append((f"functions[{i}]", [f.arg_channel], w))
    elif isinstance(node, P.UnnestNode):
        out.append(
            ("array_channels", node.array_channels, len(node.child.fields))
        )
    elif isinstance(node, (P.SortNode, P.TopNNode)):
        out.append(
            ("keys", [k.channel for k in node.keys], len(node.child.fields))
        )
    elif isinstance(node, P.ExchangeNode):
        out.append(
            ("hash_channels", node.hash_channels, len(node.child.fields))
        )
    elif isinstance(node, P.MatchRecognizeNode):
        w = len(node.child.fields)
        out.append(("partition_channels", node.partition_channels, w))
        out.append(("order_keys", [k.channel for k in node.order_keys], w))
        out.append(("shifts", [c for c, _ in node.shifts], w))
    return out


# -- checker 1: references / arity -------------------------------------------


def _check_refs(root: P.PlanNode) -> List[Violation]:
    out: List[Violation] = []
    for path, node in _walk(root):
        exp = _expected_width(node)
        if exp is not None and len(node.fields) != exp:
            out.append(Violation(
                "refs", path,
                f"output width {len(node.fields)} != expected {exp}",
            ))
        if isinstance(node, P.ValuesNode):
            for i, row in enumerate(node.rows):
                if len(row) != len(node.fields):
                    out.append(Violation(
                        "refs", path,
                        f"rows[{i}] width {len(row)} != {len(node.fields)}",
                    ))
        if isinstance(node, P.OutputNode) and len(node.names) != len(node.fields):
            out.append(Violation(
                "refs", path,
                f"{len(node.names)} names for {len(node.fields)} fields",
            ))
        if isinstance(node, P.UnionAllNode):
            for i, inp in enumerate(node.inputs):
                if len(inp.fields) != len(node.fields):
                    out.append(Violation(
                        "refs", path,
                        f"inputs[{i}] width {len(inp.fields)} != "
                        f"{len(node.fields)}",
                    ))
        if isinstance(node, P.JoinNode) and (
            len(node.left_keys) != len(node.right_keys)
        ):
            out.append(Violation(
                "refs", path,
                f"{len(node.left_keys)} left keys vs "
                f"{len(node.right_keys)} right keys",
            ))
        for label, chans, width in _channel_lists(node):
            for c in chans:
                if not (0 <= c < width):
                    out.append(Violation(
                        "refs", path,
                        f"{label} channel {c} outside input width {width}",
                    ))
        for label, expr, schema in _node_exprs(node):
            for e in _expr_walk(expr):
                if isinstance(e, ir.InputRef) and not (
                    0 <= e.index < len(schema)
                ):
                    out.append(Violation(
                        "refs", path,
                        f"{label}: {e!r} outside input width {len(schema)}",
                    ))
    return out


# -- checker 2: types ---------------------------------------------------------

# scalar names whose result is definitionally BOOLEAN; "and"/"or"/"not"
# additionally require BOOLEAN arguments
_BOOLEAN_RESULT = frozenset(
    ("and", "or", "not", "eq", "ne", "lt", "le", "gt", "ge", "is_null")
)
_BOOLEAN_ARGS = frozenset(("and", "or", "not"))


def _is_unknown(t: T.DataType) -> bool:
    return t.kind == T.TypeKind.UNKNOWN


def _check_expr_types(
    label: str, expr: ir.Expr, schema: Tuple[P.Field, ...], path: str,
    out: List[Violation],
) -> None:
    for e in _expr_walk(expr):
        if isinstance(e, ir.InputRef):
            if 0 <= e.index < len(schema) and e.type != schema[e.index].type:
                out.append(Violation(
                    "types", path,
                    f"{label}: {e!r} but input channel {e.index} is "
                    f"{schema[e.index].type}",
                ))
        elif isinstance(e, ir.Call):
            if e.name in _BOOLEAN_RESULT and e.type != T.BOOLEAN:
                out.append(Violation(
                    "types", path,
                    f"{label}: {e.name}(...) typed {e.type}, not boolean",
                ))
            if e.name in _BOOLEAN_ARGS:
                for a in e.args:
                    if a.type != T.BOOLEAN and not _is_unknown(a.type):
                        out.append(Violation(
                            "types", path,
                            f"{label}: {e.name} argument typed {a.type}",
                        ))
        elif isinstance(e, ir.Case):
            for r in e.results:
                if r.type != e.type and not (
                    _is_unknown(r.type) or _is_unknown(e.type)
                ):
                    out.append(Violation(
                        "types", path,
                        f"{label}: CASE result typed {r.type}, "
                        f"node typed {e.type}",
                    ))


def _agg_partial_fields(node: P.AggregateNode) -> Optional[List[P.Field]]:
    """Expected partial-step output fields (partial_output_schema shape);
    None when the state layout can't be derived (unknown kind)."""
    from trino_tpu.sql.fragmenter import _partial_fields

    try:
        return _partial_fields(node, node.child)
    except Exception:
        return None


def _check_types(root: P.PlanNode) -> List[Violation]:
    out: List[Violation] = []
    for path, node in _walk(root):
        for label, expr, schema in _node_exprs(node):
            _check_expr_types(label, expr, schema, path, out)

        def expect(i: int, t: T.DataType, what: str) -> None:
            if i < len(node.fields) and node.fields[i].type != t:
                out.append(Violation(
                    "types", path,
                    f"fields[{i}] is {node.fields[i].type}, {what} is {t}",
                ))

        if isinstance(node, P.FilterNode):
            if node.predicate.type != T.BOOLEAN:
                out.append(Violation(
                    "types", path,
                    f"predicate typed {node.predicate.type}, not boolean",
                ))
            for i, f in enumerate(node.child.fields):
                expect(i, f.type, f"child fields[{i}]")
        elif isinstance(node, P.ProjectNode):
            for i, e in enumerate(node.exprs):
                expect(i, e.type, f"exprs[{i}]")
        elif isinstance(node, P.AggregateNode):
            cf = node.child.fields
            k = len(node.group_channels)
            if node.step == "partial":
                pf = _agg_partial_fields(node)
                if pf is not None:
                    for i, f in enumerate(pf):
                        expect(i, f.type, f"partial state fields[{i}]")
            else:
                for i, c in enumerate(node.group_channels):
                    if node.step == "final":
                        # final consumes the partial wire layout: keys
                        # arrive first, at positions 0..k-1
                        if c < len(cf):
                            expect(i, cf[c].type, f"group key channel {c}")
                    elif c < len(cf):
                        expect(i, cf[c].type, f"group key channel {c}")
                for i, a in enumerate(node.aggs):
                    expect(k + i, a.out_type, f"aggs[{i}].out_type")
        elif isinstance(node, P.JoinNode):
            lf, rf = node.left.fields, node.right.fields
            for lk, rk in zip(node.left_keys, node.right_keys):
                if lk < len(lf) and rk < len(rf) and (
                    lf[lk].type != rf[rk].type
                ):
                    out.append(Violation(
                        "types", path,
                        f"join key L{lk} {lf[lk].type} != "
                        f"R{rk} {rf[rk].type}",
                    ))
            if node.kind in ("semi", "anti"):
                expected = lf
            elif node.kind in ("mark", "mark_exists"):
                expected = lf + (P.Field("mark", T.BOOLEAN),)
            else:
                expected = lf + rf
            for i, f in enumerate(expected):
                expect(i, f.type, f"join input fields[{i}]")
        elif isinstance(node, P.WindowNode):
            base = len(node.child.fields)
            for i, f in enumerate(node.child.fields):
                expect(i, f.type, f"child fields[{i}]")
            for i, fn in enumerate(node.functions):
                expect(base + i, fn.out_type, f"functions[{i}].out_type")
        elif isinstance(
            node,
            (P.SortNode, P.TopNNode, P.LimitNode, P.EnforceSingleRowNode,
             P.OutputNode, P.ExchangeNode),
        ):
            for i, f in enumerate(node.children()[0].fields):
                expect(i, f.type, f"child fields[{i}]")
        elif isinstance(node, P.UnionAllNode):
            for j, inp in enumerate(node.inputs):
                for i, f in enumerate(inp.fields):
                    if i < len(node.fields) and node.fields[i].type != f.type:
                        out.append(Violation(
                            "types", path,
                            f"inputs[{j}].fields[{i}] is {f.type}, "
                            f"output is {node.fields[i].type}",
                        ))
    return out


# -- checker 3: structure -----------------------------------------------------


def _check_structure(
    root: P.PlanNode, fragmented: bool = False
) -> List[Violation]:
    out: List[Violation] = []
    seen: Dict[int, str] = {}
    on_path: Set[int] = set()

    def visit(node: P.PlanNode, path: str) -> None:
        name = type(node).__name__.replace("Node", "")
        here = f"{path}/{name}" if path else name
        key = id(node)
        if key in on_path:
            out.append(Violation("structure", here, "cycle in plan tree"))
            return
        if type(node).__name__ == "GroupRef":
            out.append(Violation(
                "structure", here,
                "GroupRef leaked out of the optimizer memo",
            ))
            return
        if node.children() and key in seen:
            # interior-node sharing: two parents point at the SAME
            # object (the NoDuplicatePlanNodeIds analogue — node
            # identity doubles as the node id here, and id()-keyed
            # consumers like StatsCalculator's memo assume tree shape)
            out.append(Violation(
                "structure", here,
                f"duplicate node object (also at {seen[key]})",
            ))
            return
        seen[key] = here
        if fragmented and isinstance(node, P.ExchangeNode):
            out.append(Violation(
                "structure", here,
                "ExchangeNode survived fragmentation",
            ))
        on_path.add(key)
        for i, c in enumerate(node.children()):
            tag = _child_tag(node, i)
            visit(c, here + (f"[{tag}]" if tag else ""))
        on_path.discard(key)

    visit(root, "")
    return out


# -- checker 4: exchange keys -------------------------------------------------


def _is_tstz(t: T.DataType) -> bool:
    return t.kind == T.TypeKind.TIMESTAMP_TZ


def _masked_name(f: P.Field) -> bool:
    # canonicalize_tstz_keys names its zone-masked projections "<x>$utc"
    return bool(f.name) and f.name.endswith("$utc")


def _check_exchange_keys(root: P.PlanNode) -> List[Violation]:
    out: List[Violation] = []
    for path, node in _walk(root):
        if isinstance(node, P.ExchangeNode) and node.kind == "repartition":
            cf = node.child.fields
            for c in node.hash_channels:
                if 0 <= c < len(cf) and _is_tstz(cf[c].type) and not (
                    _masked_name(cf[c])
                ):
                    out.append(Violation(
                        "exchange_keys", path,
                        f"repartition hash channel {c} "
                        f"({cf[c].name}: {cf[c].type}) is not "
                        "zone-mask-canonicalized (expected a `$utc` "
                        "projection from canonicalize_tstz_keys)",
                    ))
        if isinstance(node, P.JoinNode):
            sides = []
            for side in (node.left, node.right):
                if isinstance(side, P.ExchangeNode) and (
                    side.kind == "repartition"
                ):
                    cf = side.child.fields
                    sides.append([
                        cf[c].type for c in side.hash_channels
                        if 0 <= c < len(cf)
                    ])
                else:
                    sides.append(None)
            lt, rt = sides
            if lt is not None and rt is not None:
                if len(lt) != len(rt):
                    out.append(Violation(
                        "exchange_keys", path,
                        f"{len(lt)} left vs {len(rt)} right partition keys",
                    ))
                else:
                    for i, (a, b) in enumerate(zip(lt, rt)):
                        if a != b:
                            out.append(Violation(
                                "exchange_keys", path,
                                f"partition key {i}: left hashes {a}, "
                                f"right hashes {b} — rows land on "
                                "different tasks",
                            ))
    return out


# -- logical pipeline ---------------------------------------------------------

LOGICAL_CHECKERS: Tuple[Tuple[str, Callable], ...] = (
    ("refs", _check_refs),
    ("types", _check_types),
    ("structure", _check_structure),
    ("exchange_keys", _check_exchange_keys),
)


def collect_violations(root: P.PlanNode) -> List[Violation]:
    """All logical-plan violations, for reporting paths (bench
    --validate-corpus); validate_logical raises on the first instead."""
    out: List[Violation] = []
    for _, check in LOGICAL_CHECKERS:
        out.extend(check(root))
    return out


def validate_logical(
    root: P.PlanNode,
    stage: Optional[str] = None,
    rule: Optional[str] = None,
) -> None:
    """Run every logical checker; raise PlanValidationError on the first
    violation (PlanSanityChecker.validateIntermediatePlan analogue)."""
    for v in collect_violations(root):
        raise PlanValidationError(v.checker, v.node_path, v.message,
                                  rule=rule, stage=stage)


# -- fragment-level validation ------------------------------------------------


def _fragment_violations(subplan) -> List[Violation]:
    frags = {f.id: f for f in subplan.all_fragments()}
    out: List[Violation] = []
    ids = [f.id for f in subplan.all_fragments()]
    if len(ids) != len(set(ids)):
        out.append(Violation(
            "structure", "SubPlan", f"duplicate fragment ids: {sorted(ids)}"
        ))
    for f in frags.values():
        fpath = f"Fragment {f.id}"
        for _, check in LOGICAL_CHECKERS:
            for v in check(f.root):
                out.append(dataclasses.replace(
                    v, node_path=f"{fpath}/{v.node_path}"
                ))
        for v in _check_structure(f.root, fragmented=True):
            if "ExchangeNode" in v.message:
                out.append(dataclasses.replace(
                    v, node_path=f"{fpath}/{v.node_path}"
                ))
        # consumer-side remote source checks
        for path, node in _walk(f.root):
            if not isinstance(node, P.RemoteSourceNode):
                continue
            here = f"{fpath}/{path}"
            for fid in node.fragment_ids:
                prod = frags.get(fid)
                if prod is None:
                    out.append(Violation(
                        "structure", here,
                        f"dangling reference to fragment {fid} "
                        f"(existing: {sorted(frags)})",
                    ))
                    continue
                pf = prod.root.fields
                if len(pf) != len(node.fields):
                    out.append(Violation(
                        "structure", here,
                        f"width {len(node.fields)} != producer fragment "
                        f"{fid} width {len(pf)}",
                    ))
                else:
                    for i, (a, b) in enumerate(zip(node.fields, pf)):
                        if a.type != b.type:
                            out.append(Violation(
                                "structure", here,
                                f"fields[{i}] {a.type} != producer "
                                f"fragment {fid} fields[{i}] {b.type}",
                            ))
                if tuple(node.merge_keys) != tuple(prod.output_merge_keys):
                    out.append(Violation(
                        "structure", here,
                        f"merge keys {node.merge_keys} != producer "
                        f"fragment {fid} {prod.output_merge_keys}",
                    ))
        # every hash producer feeding one consumer fragment must agree
        # on the partition-key dtype vector: the schedulers route
        # partition p of EVERY input to consumer task p, so two inputs
        # hashing different key types desynchronize silently
        hash_producers: List[Tuple[int, List[T.DataType]]] = []

        def gather(n):
            if isinstance(n, P.RemoteSourceNode):
                for fid in n.fragment_ids:
                    prod = frags.get(fid)
                    if prod is not None and prod.output_kind == "hash":
                        pf = prod.root.fields
                        hash_producers.append((fid, [
                            pf[c].type for c in prod.output_channels
                            if 0 <= c < len(pf)
                        ]))
            for c in n.children():
                gather(c)

        gather(f.root)
        for fid, ktypes in hash_producers[1:]:
            fid0, k0 = hash_producers[0]
            if ktypes != k0:
                out.append(Violation(
                    "exchange_keys", fpath,
                    f"hash inputs disagree: fragment {fid0} partitions on "
                    f"{[str(t) for t in k0]}, fragment {fid} on "
                    f"{[str(t) for t in ktypes]}",
                ))
    # producer-side: tstz output partition keys must be canonicalized
    for f in frags.values():
        if f.output_kind != "hash":
            continue
        pf = f.root.fields
        for c in f.output_channels:
            if 0 <= c < len(pf) and _is_tstz(pf[c].type) and not (
                _masked_name(pf[c])
            ):
                out.append(Violation(
                    "exchange_keys", f"Fragment {f.id}",
                    f"hash output channel {c} ({pf[c].name}: "
                    f"{pf[c].type}) is not zone-mask-canonicalized",
                ))
    return out


def collect_subplan_violations(subplan) -> List[Violation]:
    return _fragment_violations(subplan)


def validate_subplan(subplan, rule: Optional[str] = None) -> None:
    """Fragmented-plan validation (run after sql/fragmenter.py)."""
    for v in _fragment_violations(subplan):
        raise PlanValidationError(
            v.checker, v.node_path, v.message, rule=rule, stage="fragmenter"
        )


# -- checker 5: determinism ---------------------------------------------------


def check_plan_determinism(
    plan_once: Callable[[], P.PlanNode], what: str = "plan"
) -> None:
    """PlanDeterminismChecker analogue: run the full planning pipeline
    twice over the same AST; the EXPLAIN renderings must be
    byte-identical (a nondeterministic rule poisons the plan cache and
    makes EXPLAIN lie about what executed)."""
    a = P.explain_text(plan_once())
    b = P.explain_text(plan_once())
    if a == b:
        return
    for la, lb in zip(a.splitlines(), b.splitlines()):
        if la != lb:
            raise PlanValidationError(
                "determinism", "Output",
                f"{what}: two plannings diverge: {la.strip()!r} vs "
                f"{lb.strip()!r}",
            )
    raise PlanValidationError(
        "determinism", "Output",
        f"{what}: two plannings differ in length "
        f"({len(a.splitlines())} vs {len(b.splitlines())} lines)",
    )


def check_sql_stability(sql: str, what: str = "statement") -> None:
    """Formatter leg of the determinism checker: formatting must be a
    fixpoint (format(parse(format(parse(sql)))) == format(parse(sql))).
    Prepared-statement plan-cache keys are formatted text (engine.py),
    so an unstable formatter silently splits the cache per rendering."""
    from trino_tpu.sql.formatter import format_statement
    from trino_tpu.sql.parser import parse

    once = format_statement(parse(sql))
    twice = format_statement(parse(once))
    if once != twice:
        raise PlanValidationError(
            "determinism", "SQL",
            f"{what}: formatter is not idempotent: {once!r} reformats "
            f"to {twice!r}",
        )


# -- compile-churn static analyzer -------------------------------------------


@dataclasses.dataclass(frozen=True)
class Lowering:
    """One expected XLA lowering: the (operator, padded capacity class,
    dtype signature) key jax.jit caches compiled programs under in the
    static-shape discipline. `retry_variant` marks classes that only
    appear when dynamic-filter pruning re-buckets capacities across
    retry attempts — the jit-churn source ROADMAP PR 4 names."""

    operator: str
    capacity: int
    dtypes: Tuple[str, ...]
    retry_variant: bool = False
    # any column in the class is array/map/row-typed: no scalar device
    # layout exists, so the class is ineligible for zero-batch warmup
    # AND for resident pinning (resident/fastlane skips it) — the census
    # names these classes instead of letting them vanish silently
    nested: bool = False


def nested_column_types(types) -> List[str]:
    """The nested-kind entries in a column-type set — the shared
    eligibility predicate for warmup and resident pinning. Non-empty
    means 'skip, and say so' (resident.skips_nested / census [nested]
    marker), never a silent drop."""
    return [str(t) for t in types if getattr(t, "is_nested", False)]


def _sig(fields: Sequence[P.Field]) -> Tuple[str, ...]:
    return tuple(str(f.type) for f in fields)


def _cap(rows: float, batch_rows: int, ladder=None) -> int:
    from trino_tpu.block import bucket_capacity

    n = int(min(max(rows, 1.0), float(batch_rows)))
    if ladder is not None:
        # snap through the session's capacity ladder so the census
        # predicts the same classes a stabilized scan will produce
        return ladder.rung(n)
    return bucket_capacity(n)


def _tail_rows(rows: float, batch_rows: int) -> float:
    """Rows in the final (smaller) chunk of a table larger than
    batch_rows — 0 when the table fits one chunk or divides evenly."""
    r = int(rows)
    if r > batch_rows and r % batch_rows:
        return float(r % batch_rows)
    return 0.0


_FUSE_CONSUMERS = (P.AggregateNode, P.SortNode, P.TopNNode)


def shape_census(
    root: P.PlanNode,
    catalogs,
    batch_rows: int = 1 << 20,
    dynamic_filtering: bool = True,
    stats=None,
    ladder=None,
) -> List[Lowering]:
    """Enumerate the distinct lowerings this (fragment) plan will
    request, mirroring LocalPlanner's operator selection and fusion:
    consecutive Filter/Project stages share one FilterProjectOperator
    program, and one feeding directly into an Aggregate/Sort/TopN runs
    inside the consumer's kernel (pre_fn) and compiles no program of its
    own. Capacities come from the stats framework, so the census is as
    exact as the connector's row counts. Tables larger than batch_rows
    scan in batch_rows chunks plus one smaller tail chunk, so scans
    (and filter/project chains directly over them) contribute a tail
    capacity class too. `ladder` (compile.shapes.CapacityLadder) snaps
    predicted capacities onto the session's stabilization ladder."""
    if stats is None:
        from trino_tpu.sql.stats import StatsCalculator

        stats = StatsCalculator(catalogs)
    classes: List[Lowering] = []

    def rows(node: P.PlanNode) -> float:
        try:
            return stats.stats(node).row_count
        except Exception:
            return float(batch_rows)

    def add(op: str, rc: float, fields, retry_variant: bool = False):
        classes.append(
            Lowering(
                op, _cap(rc, batch_rows, ladder), _sig(fields), retry_variant,
                nested=bool(
                    nested_column_types([f.type for f in fields])
                ),
            )
        )

    def visit(node: P.PlanNode, fused_into_consumer: bool = False) -> None:
        if isinstance(node, (P.OutputNode, P.ExchangeNode)):
            visit(node.child, fused_into_consumer)
            return
        if isinstance(node, (P.FilterNode, P.ProjectNode)):
            # walk to the bottom of the maximal Filter/Project chain
            bottom = node
            while isinstance(bottom.child, (P.FilterNode, P.ProjectNode)):
                bottom = bottom.child
            if not fused_into_consumer:
                # filters keep capacity (live-mask discipline): the
                # chain's class is the INPUT capacity at the chain's
                # output signature
                src = rows(bottom.child)
                add("FilterProjectOperator", src, node.fields)
                if isinstance(bottom.child, P.ScanNode):
                    tail = _tail_rows(src, batch_rows)
                    if tail:
                        add("FilterProjectOperator", tail, node.fields)
            visit(bottom.child)
            return
        if isinstance(node, P.ScanNode):
            rc = rows(node)
            add("TableScanOperator", rc, node.fields)
            tail = _tail_rows(rc, batch_rows)
            if tail:
                add("TableScanOperator", tail, node.fields)
            return
        if isinstance(node, P.ValuesNode):
            add("ValuesOperator", float(len(node.rows)), node.fields)
            return
        if isinstance(node, P.RemoteSourceNode):
            add("RemoteSourceOperator", rows(node), node.fields)
            return
        if isinstance(node, P.AggregateNode):
            if any(a.distinct for a in node.aggs):
                add("HashAggregationOperator", rows(node.child), node.fields)
            add("HashAggregationOperator", rows(node), node.fields)
            visit(node.child, fused_into_consumer=True)
            return
        if isinstance(node, (P.SortNode, P.TopNNode)):
            op = ("TopNOperator" if isinstance(node, P.TopNNode)
                  else "SortOperator")
            add(op, rows(node), node.fields)
            visit(node.child, fused_into_consumer=True)
            return
        if isinstance(node, P.JoinNode):
            probe_rows = rows(node.left)
            if node.kind == "cross":
                add("CrossJoinOperator", rows(node), node.fields)
            else:
                if node.kind in ("inner", "semi") and dynamic_filtering:
                    # the filter compacts probe batches to a DATA-
                    # DEPENDENT capacity; which capacity depends on which
                    # retry attempt's build side survives, so every
                    # pruned class is a fresh lowering no warm run covers
                    add("DynamicFilterOperator", probe_rows,
                        node.left.fields, retry_variant=True)
                # an equi-join's output rides at the bucketed MATCH
                # capacity, which is data-dependent: selective keys land
                # near the output-row estimate, FK-ish multiplicity
                # lands near the probe's own class. Report both ends of
                # that band (they coincide and dedup when the estimator
                # is confident) so the census bounds join churn from
                # above instead of trusting a collapsed estimate.
                add("LookupJoinOperator", rows(node), node.fields)
                add("LookupJoinOperator", probe_rows, node.fields)
            visit(node.left)
            visit(node.right)
            return
        if isinstance(node, P.WindowNode):
            add("WindowOperator", rows(node), node.fields)
        elif isinstance(node, P.UnnestNode):
            add("UnnestOperator", rows(node), node.fields)
        elif isinstance(node, P.MatchRecognizeNode):
            add("MatchRecognizeOperator", rows(node), node.fields)
        elif isinstance(node, P.LimitNode):
            add("LimitOperator", rows(node), node.fields)
        elif isinstance(node, P.EnforceSingleRowNode):
            add("EnforceSingleRowOperator", rows(node), node.fields)
        elif isinstance(node, P.UnionAllNode):
            for inp in node.inputs:
                add("BufferSource", rows(inp), inp.fields)
        for c in node.children():
            visit(c)

    visit(root)
    # distinct classes only: a repeated (op, cap, sig) hits the jit cache
    seen: Set[Lowering] = set()
    out: List[Lowering] = []
    for c in classes:
        if c not in seen:
            seen.add(c)
            out.append(c)
    return out


def census_line(classes: List[Lowering], warn_threshold: int = 0) -> str:
    """One summary line for EXPLAIN (ANALYZE) output."""
    n = len(classes)
    variants = sum(1 for c in classes if c.retry_variant)
    nested = sum(1 for c in classes if c.nested)
    line = f"expected_xla_lowerings={n}"
    if variants:
        line += f" ({variants} retry-variant)"
    if nested:
        line += f" ({nested} nested: warmup/resident-ineligible)"
    if warn_threshold and n > warn_threshold:
        line += (
            f"  WARNING: exceeds compile_churn_warn_threshold="
            f"{warn_threshold}; expect XLA recompilation stalls "
            "(see ROADMAP shape stabilization)"
        )
    return line


def census_text(
    classes: List[Lowering],
    warn_threshold: int = 0,
    observed: Optional[int] = None,
) -> str:
    """Multi-line census block: summary + one line per class."""
    lines = ["Compile-churn census: " + census_line(classes, warn_threshold)]
    if observed is not None:
        lines[0] += f" observed_shape_classes={observed}"
    for c in sorted(classes, key=lambda c: (c.operator, c.capacity)):
        mark = " [retry-variant]" if c.retry_variant else ""
        if c.nested:
            mark += " [nested]"
        lines.append(
            f"  {c.operator} cap={c.capacity} "
            f"[{', '.join(c.dtypes)}]{mark}"
        )
    return "\n".join(lines)
