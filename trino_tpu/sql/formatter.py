"""AST -> SQL renderer.

Analogue of the reference's SqlFormatter/ExpressionFormatter
(core/trino-parser/src/main/java/io/trino/sql/SqlFormatter.java and
ExpressionFormatter.java): renders every AST node back to SQL text that
re-parses to an equivalent tree. Used by the verifier/proxy for query
normalization and by EXPLAIN output; the round-trip property
(parse(format(parse(sql))) == parse(sql)) is the tested contract.

Unlike the reference's indenting pretty-printer this emits single-line
canonical text — the engine has no multi-page DDL to pretty-print, and
one-line output is what the test oracle and the verifier diff.
"""

from __future__ import annotations

from typing import Optional

from . import ast

_IDENT_SAFE = set("abcdefghijklmnopqrstuvwxyz0123456789_")


def _ident(part: str) -> str:
    """Quote an identifier part unless it is a plain lowercase name."""
    if part and part[0].isalpha() and all(c in _IDENT_SAFE for c in part):
        return part
    return '"' + part.replace('"', '""') + '"'


def _name(parts) -> str:
    return ".".join(_ident(p) for p in parts)


def _str(value: str) -> str:
    return "'" + value.replace("'", "''") + "'"


# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------

# binding powers mirror the parser's Pratt table so parentheses are
# emitted exactly where re-parsing needs them; keys are the parser's
# normalized op names (parser.py:616-710)
_PREC = {
    "or": 1, "and": 2,
    "eq": 4, "ne": 4, "lt": 4, "le": 4, "gt": 4, "ge": 4,
    "is_distinct": 4,
    "add": 6, "sub": 6,
    "mul": 7, "div": 7, "mod": 7,
}

_OP_TEXT = {
    "or": "OR", "and": "AND",
    "eq": "=", "ne": "<>", "lt": "<", "le": "<=", "gt": ">", "ge": ">=",
    "is_distinct": "IS DISTINCT FROM",
    "add": "+", "sub": "-", "mul": "*", "div": "/", "mod": "%",
}


def format_expression(e: ast.Expression) -> str:
    if isinstance(e, ast.Parameter):
        return "?"
    return _expr(e, 0)


def _maybe_paren(text: str, prec: int, limit: int) -> str:
    return f"({text})" if prec < limit else text


def _expr(e, limit: int = 0) -> str:
    if isinstance(e, ast.Parameter):
        return "?"
    if isinstance(e, ast.Identifier):
        return _name(e.parts)
    if isinstance(e, ast.NumberLiteral):
        return e.text
    if isinstance(e, ast.StringLiteral):
        return _str(e.value)
    if isinstance(e, ast.BooleanLiteral):
        return "TRUE" if e.value else "FALSE"
    if isinstance(e, ast.NullLiteral):
        return "NULL"
    if isinstance(e, ast.DateLiteral):
        return f"DATE {_str(e.value)}"
    if isinstance(e, ast.TimestampLiteral):
        return f"TIMESTAMP {_str(e.value)}"
    if isinstance(e, ast.IntervalLiteral):
        sign = "- " if e.sign < 0 else ""
        return f"INTERVAL {sign}{_str(e.value)} {e.unit.upper()}"
    if isinstance(e, ast.Star):
        return f"{_ident(e.qualifier)}.*" if e.qualifier else "*"
    if isinstance(e, ast.BinaryOp):
        prec = _PREC[e.op]
        kw = _OP_TEXT[e.op]
        # left-assoc: right side needs one more level of binding
        text = f"{_expr(e.left, prec)} {kw} {_expr(e.right, prec + 1)}"
        return _maybe_paren(text, prec, limit)
    if isinstance(e, ast.UnaryOp):
        if e.op == "not":
            return _maybe_paren(f"NOT {_expr(e.operand, 3)}", 3, limit)
        sym = "-" if e.op == "negate" else "+"
        return _maybe_paren(f"{sym}{_expr(e.operand, 8)}", 8, limit)
    if isinstance(e, ast.IsNullPredicate):
        kw = "IS NOT NULL" if e.negated else "IS NULL"
        return _maybe_paren(f"{_expr(e.operand, 4)} {kw}", 3, limit)
    if isinstance(e, ast.Between):
        kw = "NOT BETWEEN" if e.negated else "BETWEEN"
        text = (f"{_expr(e.value, 4)} {kw} {_expr(e.low, 5)}"
                f" AND {_expr(e.high, 5)}")
        return _maybe_paren(text, 3, limit)
    if isinstance(e, ast.InList):
        kw = "NOT IN" if e.negated else "IN"
        opts = ", ".join(_expr(o) for o in e.options)
        return _maybe_paren(f"{_expr(e.value, 4)} {kw} ({opts})", 3, limit)
    if isinstance(e, ast.InSubquery):
        kw = "NOT IN" if e.negated else "IN"
        return _maybe_paren(
            f"{_expr(e.value, 4)} {kw} ({format_query(e.query)})", 3, limit
        )
    if isinstance(e, ast.Exists):
        text = f"EXISTS ({format_query(e.query)})"
        return f"NOT {text}" if e.negated else text
    if isinstance(e, ast.ScalarSubquery):
        return f"({format_query(e.query)})"
    if isinstance(e, ast.Like):
        kw = "NOT LIKE" if e.negated else "LIKE"
        text = f"{_expr(e.value, 4)} {kw} {_expr(e.pattern, 5)}"
        if e.escape is not None:
            text += f" ESCAPE {_expr(e.escape, 5)}"
        return _maybe_paren(text, 3, limit)
    if isinstance(e, ast.FunctionCall):
        inner = ", ".join(_expr(a) for a in e.args)
        if e.distinct:
            inner = "DISTINCT " + inner
        return f"{e.name}({inner})"
    if isinstance(e, ast.WindowCall):
        args = ", ".join(_expr(a) for a in e.args)
        return f"{e.name}({args}) OVER ({_window_spec(e.spec)})"
    if isinstance(e, ast.Extract):
        return f"EXTRACT({e.field.upper()} FROM {_expr(e.operand)})"
    if isinstance(e, ast.Cast):
        return f"CAST({_expr(e.operand)} AS {_type(e.target)})"
    if isinstance(e, ast.Case):
        parts = ["CASE"]
        if e.operand is not None:
            parts.append(_expr(e.operand))
        for w in e.whens:
            parts.append(f"WHEN {_expr(w.condition)} THEN {_expr(w.result)}")
        if e.default is not None:
            parts.append(f"ELSE {_expr(e.default)}")
        parts.append("END")
        return " ".join(parts)
    if isinstance(e, ast.ArrayLiteral):
        return "ARRAY[" + ", ".join(_expr(x) for x in e.elements) + "]"
    raise NotImplementedError(f"cannot format {type(e).__name__}")


def _type(t: ast.TypeName) -> str:
    if t.params:
        return f"{t.name}({', '.join(str(p) for p in t.params)})"
    return t.name


def _window_spec(spec: ast.WindowSpec) -> str:
    parts = []
    if spec.partition_by:
        parts.append(
            "PARTITION BY " + ", ".join(_expr(x) for x in spec.partition_by)
        )
    if spec.order_by:
        parts.append(
            "ORDER BY " + ", ".join(_sort_item(s) for s in spec.order_by)
        )
    if spec.frame == "rows":
        parts.append("ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW")
    elif spec.frame == "partition" and spec.order_by:
        parts.append(
            "ROWS BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING"
        )
    return " ".join(parts)


def _sort_item(s: ast.SortItem) -> str:
    text = _expr(s.expr)
    if s.descending:
        text += " DESC"
    if s.nulls_first is not None:
        text += " NULLS FIRST" if s.nulls_first else " NULLS LAST"
    return text


# ---------------------------------------------------------------------------
# relations
# ---------------------------------------------------------------------------


def _relation(r: ast.Relation) -> str:
    if isinstance(r, ast.TableRef):
        text = _name(r.name)
        if r.alias:
            text += f" AS {_ident(r.alias)}"
        return text
    if isinstance(r, ast.SubqueryRelation):
        text = f"({format_query(r.query)})"
        if r.alias:
            text += f" AS {_ident(r.alias)}"
            if r.column_aliases:
                text += "(" + ", ".join(
                    _ident(c) for c in r.column_aliases
                ) + ")"
        return text
    if isinstance(r, ast.Join):
        left = _relation(r.left)
        right = r.right
        # nested joins on the right need parens to keep associativity
        rtext = (
            f"({_relation(right)})"
            if isinstance(right, ast.Join)
            else _relation(right)
        )
        if r.kind == "cross":
            return f"{left} CROSS JOIN {rtext}"
        kw = {"inner": "INNER JOIN", "left": "LEFT JOIN",
              "right": "RIGHT JOIN", "full": "FULL JOIN"}[r.kind]
        text = f"{left} {kw} {rtext}"
        if r.using:
            text += " USING (" + ", ".join(_ident(c) for c in r.using) + ")"
        elif r.condition is not None:
            text += f" ON {_expr(r.condition)}"
        return text
    if isinstance(r, ast.MatchRecognizeRelation):
        inner = []
        if r.partition_by:
            inner.append(
                "PARTITION BY " + ", ".join(_expr(x) for x in r.partition_by)
            )
        if r.order_by:
            inner.append(
                "ORDER BY " + ", ".join(_sort_item(s) for s in r.order_by)
            )
        if r.measures:
            inner.append("MEASURES " + ", ".join(
                f"{_expr(m.expr)} AS {_ident(m.name)}" for m in r.measures
            ))
        inner.append(
            "ONE ROW PER MATCH" if r.rows_per_match == "one"
            else "ALL ROWS PER MATCH"
        )
        inner.append(
            "AFTER MATCH SKIP PAST LAST ROW"
            if r.after_match == "past_last"
            else "AFTER MATCH SKIP TO NEXT ROW"
        )
        inner.append(f"PATTERN ({_pattern(r.pattern)})")
        inner.append("DEFINE " + ", ".join(
            f"{_ident(v)} AS {_expr(c)}" for v, c in r.defines
        ))
        text = f"{_relation(r.input)} MATCH_RECOGNIZE ({' '.join(inner)})"
        if r.alias:
            text += f" AS {_ident(r.alias)}"
        return text
    if isinstance(r, ast.TableFunctionRelation):
        parts = []
        for a in r.args:
            parts.append(_tf_arg(a))
        for n, a in r.named_args:
            parts.append(f"{_ident(n)} => {_tf_arg(a)}")
        text = f"TABLE({_name(r.name)}({', '.join(parts)}))"
        if r.alias:
            text += f" AS {_ident(r.alias)}"
            if r.column_aliases:
                text += "(" + ", ".join(
                    _ident(c) for c in r.column_aliases
                ) + ")"
        return text
    if isinstance(r, ast.UnnestRelation):
        text = "UNNEST(" + ", ".join(_expr(a) for a in r.arrays) + ")"
        if r.ordinality:
            text += " WITH ORDINALITY"
        if r.alias:
            text += f" AS {_ident(r.alias)}"
            if r.column_aliases:
                text += "(" + ", ".join(
                    _ident(c) for c in r.column_aliases
                ) + ")"
        return text
    raise NotImplementedError(f"cannot format {type(r).__name__}")


def _pattern(node) -> str:
    kind = node[0]
    if kind == "var":
        return _ident(node[1])
    if kind == "seq":
        return " ".join(
            f"({_pattern(p)})" if p[0] == "alt" else _pattern(p)
            for p in node[1]
        )
    if kind == "alt":
        return " | ".join(_pattern(p) for p in node[1])
    inner = node[1]
    body = (
        f"({_pattern(inner)})"
        if inner[0] in ("seq", "alt")
        else _pattern(inner)
    )
    if kind == "star":
        return body + "*"
    if kind == "plus":
        return body + "+"
    if kind == "opt":
        return body + "?"
    lo, hi = node[2], node[3]
    if hi == lo:
        return f"{body}{{{lo}}}"
    return f"{body}{{{lo},{'' if hi is None else hi}}}"


def _tf_arg(a) -> str:
    if isinstance(a, ast.TableArg):
        return f"TABLE({_relation(a.relation)})"
    if isinstance(a, ast.Descriptor):
        return "DESCRIPTOR(" + ", ".join(_ident(n) for n in a.names) + ")"
    return _expr(a)


# ---------------------------------------------------------------------------
# query bodies & statements
# ---------------------------------------------------------------------------


def _group_by(spec: ast.QuerySpec) -> Optional[str]:
    if not spec.group_by:
        return None
    exprs = [_expr(g) for g in spec.group_by]
    if spec.group_by_sets is None:
        return "GROUP BY " + ", ".join(exprs)
    # grouping-set index tuples render back as explicit GROUPING SETS —
    # ROLLUP/CUBE sugar is already desugared by the parser and the
    # explicit form re-parses to the identical index sets
    sets = ", ".join(
        "(" + ", ".join(exprs[i] for i in s) + ")"
        for s in spec.group_by_sets
    )
    return f"GROUP BY GROUPING SETS ({sets})"


def _query_spec(spec: ast.QuerySpec) -> str:
    parts = ["SELECT"]
    if spec.distinct:
        parts.append("DISTINCT")
    items = []
    for it in spec.select:
        text = _expr(it.expr)
        if it.alias:
            text += f" AS {_ident(it.alias)}"
        items.append(text)
    parts.append(", ".join(items))
    if spec.from_ is not None:
        parts.append("FROM " + _relation(spec.from_))
    if spec.where is not None:
        parts.append("WHERE " + _expr(spec.where))
    gb = _group_by(spec)
    if gb:
        parts.append(gb)
    if spec.having is not None:
        parts.append("HAVING " + _expr(spec.having))
    return " ".join(parts)


def _body(body) -> str:
    if isinstance(body, ast.QuerySpec):
        return _query_spec(body)
    if isinstance(body, ast.SetOperation):
        kw = body.op.upper() + (" ALL" if body.all else "")
        left = _body(body.left)
        right = body.right
        rtext = (
            f"({_body(right)})"
            if isinstance(right, ast.SetOperation)
            else _body(right)
        )
        return f"{left} {kw} {rtext}"
    if isinstance(body, ast.ValuesBody):
        rows = ", ".join(
            "(" + ", ".join(_expr(e) for e in row) + ")"
            for row in body.rows
        )
        return "VALUES " + rows
    raise NotImplementedError(f"cannot format {type(body).__name__}")


def format_query(q: ast.Query) -> str:
    parts = []
    if q.with_:
        ctes = []
        for w in q.with_:
            head = _ident(w.name)
            if w.column_names:
                head += "(" + ", ".join(
                    _ident(c) for c in w.column_names
                ) + ")"
            ctes.append(f"{head} AS ({format_query(w.query)})")
        parts.append("WITH " + ", ".join(ctes))
    parts.append(_body(q.body))
    if q.order_by:
        parts.append(
            "ORDER BY " + ", ".join(_sort_item(s) for s in q.order_by)
        )
    if q.offset:
        parts.append(f"OFFSET {q.offset}")
    if q.limit is not None:
        parts.append(f"LIMIT {q.limit}")
    return " ".join(parts)


def format_statement(node: ast.Node) -> str:
    """Render any statement node produced by parser.parse_statement."""
    if isinstance(node, ast.Query):
        return format_query(node)
    if isinstance(node, ast.ExplainStatement):
        kw = "EXPLAIN ANALYZE" if node.analyze else "EXPLAIN"
        return f"{kw} {format_query(node.query)}"
    if isinstance(node, ast.CreateTable):
        cols = ", ".join(
            f"{_ident(n)} {_type(t)}" for n, t in node.columns
        )
        return f"CREATE TABLE {_name(node.table)} ({cols})"
    if isinstance(node, ast.CreateTableAs):
        return (
            f"CREATE TABLE {_name(node.table)} AS {format_query(node.query)}"
        )
    if isinstance(node, ast.Insert):
        cols = (
            " (" + ", ".join(_ident(c) for c in node.columns) + ")"
            if node.columns
            else ""
        )
        return f"INSERT INTO {_name(node.table)}{cols} {format_query(node.query)}"
    if isinstance(node, ast.DropTable):
        return f"DROP TABLE {_name(node.table)}"
    if isinstance(node, ast.Delete):
        text = f"DELETE FROM {_name(node.table)}"
        if node.where is not None:
            text += f" WHERE {_expr(node.where)}"
        return text
    if isinstance(node, ast.Update):
        sets = ", ".join(
            f"{_ident(c)} = {_expr(e)}" for c, e in node.assignments
        )
        text = f"UPDATE {_name(node.table)} SET {sets}"
        if node.where is not None:
            text += f" WHERE {_expr(node.where)}"
        return text
    if isinstance(node, ast.SetSession):
        return f"SET SESSION {node.name} = {node.value}"
    if isinstance(node, ast.StartTransaction):
        return "START TRANSACTION" + (
            " READ ONLY" if node.read_only else ""
        )
    if isinstance(node, ast.Commit):
        return "COMMIT"
    if isinstance(node, ast.Rollback):
        return "ROLLBACK"
    if isinstance(node, ast.ShowSession):
        return "SHOW SESSION"
    if isinstance(node, ast.ShowTables):
        if node.schema:
            return f"SHOW TABLES FROM {_name(node.schema)}"
        return "SHOW TABLES"
    if isinstance(node, ast.ShowSchemas):
        if node.catalog:
            return f"SHOW SCHEMAS FROM {_ident(node.catalog)}"
        return "SHOW SCHEMAS"
    if isinstance(node, ast.ShowColumns):
        return f"SHOW COLUMNS FROM {_name(node.table)}"
    if isinstance(node, ast.ShowFunctions):
        return "SHOW FUNCTIONS"
    raise NotImplementedError(f"cannot format {type(node).__name__}")
