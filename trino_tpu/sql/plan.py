"""Logical plan IR.

Analogue of Trino's plan-node layer (main/sql/planner/plan/, 59 classes
— SURVEY.md §2.2), reduced to the relational core the executor runs.
Conventions that keep physical planning mechanical:

- Every node's output schema is an ordered list of Field(name, type);
  expressions inside nodes are typed IR (trino_tpu.expr.ir) whose
  InputRefs index the CHILD's output channels.
- Aggregate/Join key and argument expressions are always plain channel
  references — the analyzer inserts Project nodes to materialize
  anything more complex (the HashGenerationOptimizer discipline).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from trino_tpu import types as T
from trino_tpu.expr.ir import Expr
from trino_tpu.ops.sort import SortKey


@dataclasses.dataclass(frozen=True)
class Field:
    name: Optional[str]
    type: T.DataType


class PlanNode:
    fields: Tuple[Field, ...]

    def children(self) -> Sequence["PlanNode"]:
        return ()


@dataclasses.dataclass(frozen=True)
class ScanNode(PlanNode):
    """Connector table scan (TableScanNode analogue). `columns` are the
    pruned connector column names, 1:1 with fields."""

    catalog: str
    handle: object  # connectors.spi.TableHandle
    columns: Tuple[str, ...]
    fields: Tuple[Field, ...]


@dataclasses.dataclass(frozen=True)
class ValuesNode(PlanNode):
    fields: Tuple[Field, ...]
    rows: Tuple[Tuple[object, ...], ...]  # python literal values


@dataclasses.dataclass(frozen=True)
class FilterNode(PlanNode):
    child: PlanNode
    predicate: Expr
    fields: Tuple[Field, ...]

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class ProjectNode(PlanNode):
    child: PlanNode
    exprs: Tuple[Expr, ...]
    fields: Tuple[Field, ...]

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class AggCall:
    """kind in {sum,count,count_star,avg,min,max,any} plus the holistic
    kinds {min_by,max_by,approx_percentile}; arg_channel indexes the
    child schema (None for count_star). arg2_channel is min_by/max_by's
    ordering argument; percentile is approx_percentile's fraction."""

    kind: str
    arg_channel: Optional[int]
    out_type: T.DataType
    distinct: bool = False
    arg2_channel: Optional[int] = None
    percentile: Optional[float] = None
    separator: Optional[str] = None  # listagg
    arg3_channel: Optional[int] = None  # pctl_merge bucket-max channel
    param: Optional[float] = None  # numeric_histogram/approx_most_frequent b
    post: Optional[str] = None  # fused sketch accessor: card | vq | qv


@dataclasses.dataclass(frozen=True)
class AggregateNode(PlanNode):
    """Output schema = [group key channels..., agg results...]
    (AggregationNode analogue). `step` is the AggregationNode.Step:
    single | partial (emits serialized accumulator state) | final
    (consumes state from the exchange). In partial/final steps the
    output/input layout follows operators.partial_output_schema."""

    child: PlanNode
    group_channels: Tuple[int, ...]
    aggs: Tuple[AggCall, ...]
    fields: Tuple[Field, ...]
    step: str = "single"

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class JoinNode(PlanNode):
    """kind in {inner,left,semi,anti,cross}. Left is the probe side.
    Output schema: left fields + right fields (inner/left/cross);
    left fields only (semi/anti). `residual` is typed over the
    concatenated left+right schema and runs inside the join, before
    match flags (JoinNode.filter analogue)."""

    kind: str
    left: PlanNode
    right: PlanNode
    left_keys: Tuple[int, ...]
    right_keys: Tuple[int, ...]
    residual: Optional[Expr]
    fields: Tuple[Field, ...]
    # skew-aware execution annotations (adaptive/controller.py). Both
    # are declared fields, so they ride through dataclasses.replace and
    # appear in the repr — which is what plan fingerprints, spool keys
    # and the mesh program-cache key hash, keeping annotated and plain
    # plans distinct without any explicit key plumbing.
    #
    # skew_hot_keys: observed heavy-hitter values of the (single) join
    # key; the mesh plane replicates hot BUILD rows to every shard and
    # salts hot PROBE rows across the all_to_all. spill_build: observed
    # build rows overflowed the estimate — the local planner pre-opens
    # grace partitions (hybrid hash) instead of thrashing revocation.
    skew_hot_keys: Tuple = ()
    spill_build: bool = False

    def children(self):
        return (self.left, self.right)


@dataclasses.dataclass(frozen=True)
class WindowFuncSpec:
    """One window function: kind in {row_number, rank, dense_rank,
    percent_rank, cume_dist, ntile,
    lead, lag, first_value, last_value, sum, avg, min, max, count,
    count_star}; arg_channel indexes the child schema (None for rank
    family / count_star); `offset` is lead/lag's offset or ntile's n."""

    kind: str
    arg_channel: Optional[int]
    out_type: T.DataType
    offset: int = 1


@dataclasses.dataclass(frozen=True)
class WindowNode(PlanNode):
    """Window functions over (partition, order) — WindowNode analogue.
    Output schema = child fields + one field per function. `frame`:
    "range" | "rows" | "partition" (ops/window.py semantics)."""

    child: PlanNode
    partition_channels: Tuple[int, ...]
    order_keys: Tuple[SortKey, ...]
    functions: Tuple[WindowFuncSpec, ...]
    frame: str
    fields: Tuple[Field, ...]

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class UnnestNode(PlanNode):
    """Lateral UNNEST over ARRAY-typed child columns (UnnestNode
    analogue, main/sql/planner/plan/UnnestNode.java + UnnestOperator).
    Output = child fields + one element field per array channel
    (+ ordinality). Multi-array zip pads short arrays with NULL; rows
    whose arrays are all empty produce no output (inner semantics)."""

    child: PlanNode
    array_channels: Tuple[int, ...]
    ordinality: bool
    fields: Tuple[Field, ...]

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class MeasureSpec:
    """One MATCH_RECOGNIZE measure. kind: "first" | "last" (value of
    `channel` at the first/last row tagged `var`; var None = the whole
    match) | "match_number" | "classifier"."""

    kind: str
    name: str
    out_type: T.DataType
    var: Optional[str] = None
    channel: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class MatchRecognizeNode(PlanNode):
    """Row pattern recognition (PatternRecognitionNode analogue,
    main/sql/planner/plan/PatternRecognitionNode.java). `defines` maps
    var -> typed predicate over the EXTENDED child schema (child
    channels + the shifted copies listed in `shifts`: channel c shifted
    by offset o appears at extended channel len(child.fields) + i).
    Output schema (ONE ROW PER MATCH): partition channels' fields +
    one field per measure."""

    child: PlanNode
    partition_channels: Tuple[int, ...]
    order_keys: Tuple[SortKey, ...]
    defines: Tuple[Tuple[str, Expr], ...]
    shifts: Tuple[Tuple[int, int], ...]  # (child channel, offset)
    pattern: object
    measures: Tuple[MeasureSpec, ...]
    after_match: str  # "past_last" | "next_row"
    fields: Tuple[Field, ...]

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class SortNode(PlanNode):
    child: PlanNode
    keys: Tuple[SortKey, ...]
    fields: Tuple[Field, ...]

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class TopNNode(PlanNode):
    child: PlanNode
    keys: Tuple[SortKey, ...]
    count: int
    fields: Tuple[Field, ...]

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class LimitNode(PlanNode):
    child: PlanNode
    count: Optional[int]
    offset: int
    fields: Tuple[Field, ...]

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class EnforceSingleRowNode(PlanNode):
    """Scalar-subquery cardinality guard (EnforceSingleRowOperator
    analogue): exactly one input row passes through; zero rows yield one
    all-NULL row; more than one raises at execution."""

    child: PlanNode
    fields: Tuple[Field, ...]

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class UnionAllNode(PlanNode):
    """Concatenation of same-width children (UNION ALL; distinct unions
    get an AggregateNode on top)."""

    inputs: Tuple[PlanNode, ...]
    fields: Tuple[Field, ...]

    def children(self):
        return self.inputs


@dataclasses.dataclass(frozen=True)
class OutputNode(PlanNode):
    """Root: names the result columns (OutputNode analogue)."""

    child: PlanNode
    names: Tuple[str, ...]
    fields: Tuple[Field, ...]

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class ExchangeNode(PlanNode):
    """Remote exchange in the distributed plan (ExchangeNode REMOTE scope
    + the SystemPartitioningHandle family, SURVEY.md §2.2/§2.7).
    kind: "gather" (to one task; with merge_keys = merging gather),
    "repartition" (FIXED_HASH on hash_channels), "broadcast"
    (FIXED_BROADCAST replication). Inserted by the AddExchanges pass;
    the fragmenter cuts the plan here."""

    child: PlanNode
    kind: str
    hash_channels: Tuple[int, ...]
    fields: Tuple[Field, ...]
    merge_keys: Tuple = ()

    def children(self):
        return (self.child,)


@dataclasses.dataclass(frozen=True)
class RemoteSourceNode(PlanNode):
    """Leaf of a fragment: pages arriving from producer fragments
    (RemoteSourceNode analogue)."""

    fragment_ids: Tuple[int, ...]
    fields: Tuple[Field, ...]
    merge_keys: Tuple = ()


def explain_text(node: PlanNode, indent: int = 0) -> str:
    """EXPLAIN rendering (textual plan like Trino's PlanPrinter)."""
    pad = "  " * indent
    name = type(node).__name__.replace("Node", "")
    detail = ""
    if isinstance(node, ScanNode):
        h = node.handle
        detail = f" {node.catalog}.{h.schema}.{h.table} {list(node.columns)}"
        pushed = getattr(h, "constraints", ())
        if pushed:
            def _ctext(c):
                if c.op == "or":  # multi-range: render the disjuncts
                    return c.column + " (" + " or ".join(
                        f"{op} {v!r}" for op, v in c.value
                    ) + ")"
                return f"{c.column} {c.op} {c.value!r}"

            detail += " pushed=[" + ", ".join(
                _ctext(c) for c in pushed
            ) + "]"
    elif isinstance(node, FilterNode):
        detail = f" {node.predicate!r}"
    elif isinstance(node, ProjectNode):
        detail = f" {[repr(e) for e in node.exprs]}"
    elif isinstance(node, AggregateNode):
        detail = f" keys={list(node.group_channels)} aggs={[a.kind for a in node.aggs]}"
        if node.step != "single":
            detail += f" step={node.step}"
    elif isinstance(node, ExchangeNode):
        detail = f" {node.kind}"
        if node.hash_channels:
            detail += f" on={list(node.hash_channels)}"
        if node.merge_keys:
            detail += " merge"
    elif isinstance(node, RemoteSourceNode):
        detail = f" fragments={list(node.fragment_ids)}"
    elif isinstance(node, JoinNode):
        detail = (
            f" {node.kind} L{list(node.left_keys)}=R{list(node.right_keys)}"
            + (" +residual" if node.residual is not None else "")
        )
        # skew annotations render only when present, so plans with no
        # skew stay byte-identical to the unannotated output
        if node.skew_hot_keys:
            detail += f" hot={list(node.skew_hot_keys)}"
        if node.spill_build:
            detail += " spill_build"
    elif isinstance(node, (SortNode, TopNNode)):
        detail = f" keys={[(k.channel, 'desc' if k.descending else 'asc') for k in node.keys]}"
        if isinstance(node, TopNNode):
            detail += f" n={node.count}"
    elif isinstance(node, LimitNode):
        detail = f" n={node.count} offset={node.offset}"
    elif isinstance(node, OutputNode):
        detail = f" {list(node.names)}"
    elif isinstance(node, ValuesNode) and getattr(node, "spool_key", ""):
        # adaptively materialized subtree riding along as a literal
        detail = (
            f" rows={len(node.rows)} spool={node.spool_key}"
            f" [{getattr(node, 'source_desc', '')}]"
        )
    lines = [f"{pad}{name}{detail}"]
    for c in node.children():
        lines.append(explain_text(c, indent + 1))
    return "\n".join(lines)
