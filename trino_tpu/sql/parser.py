"""SQL lexer + recursive-descent/Pratt parser -> AST.

Analogue of trino-parser's ANTLR grammar + AstBuilder
(core/trino-parser/src/main/antlr4/.../SqlBase.g4, 1,284 lines;
parser/sql/parser/AstBuilder.java:332 — SURVEY.md §2.1). A generated
parser buys nothing on this subset, so this is a hand-written Pratt
parser with Trino's precedence table; error messages carry line:col like
Trino's ParsingException.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

from trino_tpu.sql import ast

# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*|/\*.*?\*/)
  | (?P<number>\d+(\.\d*)?([eE][+-]?\d+)?|\.\d+([eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_][A-Za-z_0-9$]*)
  | (?P<op><>|!=|>=|<=|=>|->|\|\||[-+*/%(),.;=<>\[\]?{}|])
    """,
    re.VERBOSE | re.DOTALL,
)


class Token:
    __slots__ = ("kind", "text", "pos", "line", "col")

    def __init__(self, kind, text, pos, line, col):
        self.kind = kind  # number/string/ident/qident/op/eof
        self.text = text
        self.pos = pos
        self.line = line
        self.col = col

    @property
    def upper(self):
        return self.text.upper()

    def __repr__(self):
        return f"Token({self.kind}, {self.text!r} @{self.line}:{self.col})"


class ParsingError(ValueError):
    pass


def tokenize(sql: str) -> List[Token]:
    out = []
    pos = 0
    line, col = 1, 1
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            raise ParsingError(f"line {line}:{col}: unexpected character {sql[pos]!r}")
        text = m.group(0)
        kind = m.lastgroup
        if kind != "ws":
            out.append(Token(kind, text, pos, line, col))
        nl = text.count("\n")
        if nl:
            line += nl
            col = len(text) - text.rfind("\n")
        else:
            col += len(text)
        pos = m.end()
    out.append(Token("eof", "", pos, line, col))
    return out


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------

_RESERVED_STOP = {
    # words that terminate an expression / select item / relation
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "UNION",
    "INTERSECT", "EXCEPT", "ON", "JOIN", "INNER", "LEFT", "RIGHT", "FULL",
    "CROSS", "AS", "AND", "OR", "NOT", "BY", "ASC", "DESC", "NULLS", "FIRST",
    "LAST", "WHEN", "THEN", "ELSE", "END", "CASE", "BETWEEN", "IN", "LIKE",
    "IS", "NULL", "EXISTS", "DISTINCT", "ALL", "SELECT", "WITH", "USING",
    "ESCAPE", "OUTER", "MATCH_RECOGNIZE",
}

# words that can never start a bare identifier expression
_HARD_RESERVED = {
    "SELECT", "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "BY", "LIMIT",
    "OFFSET", "UNION", "INTERSECT", "EXCEPT", "JOIN", "INNER", "LEFT",
    "RIGHT", "FULL", "OUTER", "CROSS", "ON", "USING", "AND", "OR", "NOT",
    "BETWEEN", "IN", "LIKE", "IS", "WHEN", "THEN", "ELSE", "END", "AS",
    "DISTINCT", "ALL", "WITH", "ESCAPE",
}

_TYPE_NAMES = {
    "BOOLEAN", "TINYINT", "SMALLINT", "INT", "INTEGER", "BIGINT", "REAL",
    "DOUBLE", "DECIMAL", "NUMERIC", "VARCHAR", "CHAR", "DATE", "TIMESTAMP",
    "ARRAY", "MAP", "ROW",
}


class Parser:
    def __init__(self, sql: str):
        self.tokens = tokenize(sql)
        self.i = 0

    # -- token helpers --
    def peek(self, ahead: int = 0) -> Token:
        return self.tokens[min(self.i + ahead, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.tokens[self.i]
        self.i += 1
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.upper in words

    def accept_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.next()
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.accept_kw(word):
            t = self.peek()
            raise ParsingError(
                f"line {t.line}:{t.col}: expected {word}, found {t.text!r}"
            )

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.text in ops

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            t = self.peek()
            raise ParsingError(f"line {t.line}:{t.col}: expected {op!r}, found {t.text!r}")

    def error(self, msg: str) -> ParsingError:
        t = self.peek()
        return ParsingError(f"line {t.line}:{t.col}: {msg} (found {t.text!r})")

    # -- entry --
    def parse_statement(self) -> ast.Node:
        if self.at_kw("START"):
            self.next()
            self.expect_kw("TRANSACTION")
            read_only = False
            # modifiers: ISOLATION LEVEL <words>, READ ONLY / READ WRITE
            while True:
                if self.accept_kw("ISOLATION"):
                    self.expect_kw("LEVEL")
                    # READ UNCOMMITTED|COMMITTED / REPEATABLE READ /
                    # SERIALIZABLE — two-word forms consume both words
                    first = self._parse_name()
                    if first in ("read", "repeatable"):
                        self._parse_name()
                    self.accept_op(",")
                    continue
                if self.accept_kw("READ"):
                    if self.accept_kw("ONLY"):
                        read_only = True
                    else:
                        self.expect_kw("WRITE")
                    self.accept_op(",")
                    continue
                break
            stmt: ast.Node = ast.StartTransaction(read_only)
        elif self.at_kw("COMMIT"):
            self.next()
            self.accept_kw("WORK")
            stmt = ast.Commit()
        elif self.at_kw("ROLLBACK"):
            self.next()
            self.accept_kw("WORK")
            stmt = ast.Rollback()
        elif self.at_kw("PREPARE"):
            # PREPARE name FROM <statement> (tree/Prepare.java:25)
            self.next()
            pname = self._parse_name()
            self.expect_kw("FROM")
            inner = self.parse_statement()
            stmt = ast.Prepare(pname, inner, "")
        elif self.at_kw("EXECUTE"):
            self.next()
            pname = self._parse_name()
            params: List[ast.Expression] = []
            if self.accept_kw("USING"):
                params.append(self.parse_expr())
                while self.accept_op(","):
                    params.append(self.parse_expr())
            stmt = ast.ExecuteStmt(pname, tuple(params))
        elif self.at_kw("DEALLOCATE"):
            self.next()
            self.expect_kw("PREPARE")
            stmt = ast.Deallocate(self._parse_name())
        elif self.at_kw("EXPLAIN"):
            self.next()
            analyze = self.accept_kw("ANALYZE")
            stmt: ast.Node = ast.ExplainStatement(self.parse_query(), analyze)
        elif self.at_kw("SHOW"):
            stmt = self._parse_show()
        elif self.at_kw("CREATE"):
            stmt = self._parse_create()
        elif self.at_kw("INSERT"):
            self.next()
            self.expect_kw("INTO")
            table = self._parse_qualified_name()
            columns = None
            if self.at_op("(") :
                self.next()
                columns = self._parse_name_list()
            stmt = ast.Insert(table, columns, self.parse_query())
        elif self.at_kw("DELETE"):
            self.next()
            self.expect_kw("FROM")
            table = self._parse_qualified_name()
            where = None
            if self.accept_kw("WHERE"):
                where = self.parse_expr()
            stmt = ast.Delete(table, where)
        elif self.at_kw("UPDATE"):
            self.next()
            table = self._parse_qualified_name()
            self.expect_kw("SET")
            assignments = []
            while True:
                col = self._parse_name()
                self.expect_op("=")
                assignments.append((col, self.parse_expr()))
                if not self.accept_op(","):
                    break
            where = None
            if self.accept_kw("WHERE"):
                where = self.parse_expr()
            stmt = ast.Update(table, tuple(assignments), where)
        elif self.at_kw("MERGE"):
            stmt = self._parse_merge()
        elif self.at_kw("DROP"):
            self.next()
            self.expect_kw("TABLE")
            stmt = ast.DropTable(self._parse_qualified_name())
        elif self.at_kw("SET"):
            self.next()
            self.expect_kw("SESSION")
            name = self._parse_name()
            self.expect_op("=")
            t = self.next()
            value = (
                t.text[1:-1].replace("''", "'")
                if t.kind == "string"
                else t.text
            )
            stmt = ast.SetSession(name, value)
        else:
            stmt = self.parse_query()
        self.accept_op(";")
        t = self.peek()
        if t.kind != "eof":
            raise self.error("unexpected trailing input")
        return stmt

    def _parse_create(self) -> ast.Node:
        self.expect_kw("CREATE")
        self.expect_kw("TABLE")
        table = self._parse_qualified_name()
        if self.accept_kw("AS"):
            return ast.CreateTableAs(table, self.parse_query())
        self.expect_op("(")
        cols = []
        while True:
            name = self._parse_name()
            cols.append((name, self._parse_type()))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return ast.CreateTable(table, tuple(cols))

    def _parse_show(self) -> ast.Node:
        self.expect_kw("SHOW")
        if self.accept_kw("TABLES"):
            schema = None
            if self.accept_kw("FROM") or self.accept_kw("IN"):
                schema = self._parse_qualified_name()
            return ast.ShowTables(schema)
        if self.accept_kw("SCHEMAS"):
            catalog = None
            if self.accept_kw("FROM") or self.accept_kw("IN"):
                catalog = self._parse_name()
            return ast.ShowSchemas(catalog)
        if self.accept_kw("COLUMNS"):
            self.expect_kw("FROM")
            return ast.ShowColumns(self._parse_qualified_name())
        if self.accept_kw("SESSION"):
            return ast.ShowSession()
        if self.accept_kw("FUNCTIONS"):
            return ast.ShowFunctions()
        raise self.error(
            "expected TABLES, SCHEMAS, COLUMNS, SESSION or FUNCTIONS after SHOW"
        )

    # -- query --
    def parse_query(self) -> ast.Query:
        with_ = ()
        if self.accept_kw("WITH"):
            ctes = []
            while True:
                name = self._parse_name()
                colnames: Tuple[str, ...] = ()
                if self.accept_op("("):
                    colnames = self._parse_name_list()
                self.expect_kw("AS")
                self.expect_op("(")
                q = self.parse_query()
                self.expect_op(")")
                ctes.append(ast.WithQuery(name, q, colnames))
                if not self.accept_op(","):
                    break
            with_ = tuple(ctes)
        body = self._parse_query_body()
        order_by: Tuple[ast.SortItem, ...] = ()
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            items = [self._parse_sort_item()]
            while self.accept_op(","):
                items.append(self._parse_sort_item())
            order_by = tuple(items)
        offset = 0
        limit = None
        if self.accept_kw("OFFSET"):
            offset = int(self.next().text)
            self.accept_kw("ROW") or self.accept_kw("ROWS")
        if self.accept_kw("LIMIT"):
            t = self.next()
            if t.kind == "ident" and t.upper == "ALL":
                limit = None
            else:
                limit = int(t.text)
        if self.accept_kw("OFFSET"):
            offset = int(self.next().text)
            self.accept_kw("ROW") or self.accept_kw("ROWS")
        return ast.Query(body, with_, order_by, limit, offset)

    def _parse_query_body(self) -> ast.Node:
        # INTERSECT binds tighter than UNION/EXCEPT (SqlBase.g4 queryTerm)
        left = self._parse_intersect_term()
        while self.at_kw("UNION", "EXCEPT"):
            op = self.next().upper.lower()
            all_ = self.accept_kw("ALL")
            if not all_:
                self.accept_kw("DISTINCT")
            right = self._parse_intersect_term()
            left = ast.SetOperation(op, all_, left, right)
        return left

    def _parse_intersect_term(self) -> ast.Node:
        left = self._parse_query_term()
        while self.at_kw("INTERSECT"):
            self.next()
            all_ = self.accept_kw("ALL")
            if not all_:
                self.accept_kw("DISTINCT")
            right = self._parse_query_term()
            left = ast.SetOperation("intersect", all_, left, right)
        return left

    def _parse_query_term(self) -> ast.Node:
        if self.accept_op("("):
            body = self._parse_query_body()
            self.expect_op(")")
            return body
        if self.at_kw("VALUES"):
            self.next()
            rows = [self._parse_values_row()]
            while self.accept_op(","):
                rows.append(self._parse_values_row())
            return ast.ValuesBody(tuple(rows))
        return self._parse_query_spec()

    def _parse_values_row(self) -> tuple:
        self.expect_op("(")
        row = [self.parse_expr()]
        while self.accept_op(","):
            row.append(self.parse_expr())
        self.expect_op(")")
        return tuple(row)

    def _parse_query_spec(self) -> ast.QuerySpec:
        self.expect_kw("SELECT")
        distinct = False
        if self.accept_kw("DISTINCT"):
            distinct = True
        else:
            self.accept_kw("ALL")
        select = [self._parse_select_item()]
        while self.accept_op(","):
            select.append(self._parse_select_item())
        from_ = None
        if self.accept_kw("FROM"):
            from_ = self._parse_relation()
        where = None
        if self.accept_kw("WHERE"):
            where = self.parse_expr()
        group_by: Tuple[ast.Expression, ...] = ()
        group_by_sets = None
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            group_by, group_by_sets = self._parse_group_by()
        having = None
        if self.accept_kw("HAVING"):
            having = self.parse_expr()
        return ast.QuerySpec(
            tuple(select), distinct, from_, where, group_by, having,
            group_by_sets,
        )

    def _parse_group_by(self):
        """Plain list, ROLLUP(...), CUBE(...) or GROUPING SETS
        (SqlBase.g4 groupingElement)."""
        if self.at_kw("ROLLUP", "CUBE"):
            kind = self.next().upper
            self.expect_op("(")
            exprs = [self.parse_expr()]
            while self.accept_op(","):
                exprs.append(self.parse_expr())
            self.expect_op(")")
            n = len(exprs)
            if kind == "ROLLUP":
                sets = tuple(tuple(range(i)) for i in range(n, -1, -1))
            else:  # CUBE: all subsets, larger first
                import itertools as _it

                sets = tuple(
                    s
                    for size in range(n, -1, -1)
                    for s in _it.combinations(range(n), size)
                )
            return tuple(exprs), sets
        if self.at_kw("GROUPING"):
            self.next()
            self.expect_kw("SETS")
            self.expect_op("(")
            raw_sets = []
            while True:
                self.expect_op("(")
                one = []
                if not self.at_op(")"):
                    one.append(self.parse_expr())
                    while self.accept_op(","):
                        one.append(self.parse_expr())
                self.expect_op(")")
                raw_sets.append(tuple(one))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            exprs: List[ast.Expression] = []
            index_sets = []
            for s in raw_sets:
                idx = []
                for e in s:
                    if e not in exprs:
                        exprs.append(e)
                    idx.append(exprs.index(e))
                index_sets.append(tuple(idx))
            return tuple(exprs), tuple(index_sets)
        items = [self.parse_expr()]
        while self.accept_op(","):
            items.append(self.parse_expr())
        return tuple(items), None

    def _parse_select_item(self) -> ast.SelectItem:
        if self.at_op("*"):
            self.next()
            return ast.SelectItem(ast.Star())
        # alias.*
        if (
            self.peek().kind in ("ident", "qident")
            and self.peek(1).kind == "op"
            and self.peek(1).text == "."
            and self.peek(2).kind == "op"
            and self.peek(2).text == "*"
        ):
            qual = self._parse_name()
            self.next()
            self.next()
            return ast.SelectItem(ast.Star(qual))
        expr = self.parse_expr()
        alias = None
        if self.accept_kw("AS"):
            alias = self._parse_name()
        elif self.peek().kind in ("ident", "qident") and self.peek().upper not in _RESERVED_STOP:
            alias = self._parse_name()
        return ast.SelectItem(expr, alias)

    def _parse_sort_item(self) -> ast.SortItem:
        expr = self.parse_expr()
        descending = False
        if self.accept_kw("DESC"):
            descending = True
        else:
            self.accept_kw("ASC")
        nulls_first = None
        if self.accept_kw("NULLS"):
            if self.accept_kw("FIRST"):
                nulls_first = True
            else:
                self.expect_kw("LAST")
                nulls_first = False
        return ast.SortItem(expr, descending, nulls_first)

    def _parse_merge(self) -> "ast.Merge":
        """MERGE INTO target [[AS] alias] USING source ON cond
        WHEN [NOT] MATCHED [AND c] THEN UPDATE SET ... | DELETE |
        INSERT [(cols)] VALUES (...)  (parser/sql/tree/Merge.java)."""
        self.next()
        self.expect_kw("INTO")
        table = self._parse_qualified_name()
        target_alias = None
        if self.accept_kw("AS"):
            target_alias = self._parse_name()
        elif self.peek().kind == "ident" and not self.at_kw("USING"):
            target_alias = self._parse_name()
        self.expect_kw("USING")
        source = self._parse_table_primary()
        self.expect_kw("ON")
        on = self.parse_expr()
        clauses = []
        while self.at_kw("WHEN"):
            self.next()
            matched = not self.accept_kw("NOT")
            self.expect_kw("MATCHED")
            cond = None
            if self.accept_kw("AND"):
                cond = self.parse_expr()
            self.expect_kw("THEN")
            if matched and self.accept_kw("UPDATE"):
                self.expect_kw("SET")
                assignments = []
                while True:
                    col = self._parse_name()
                    self.expect_op("=")
                    assignments.append((col, self.parse_expr()))
                    if not self.accept_op(","):
                        break
                clauses.append(ast.MergeClause(
                    True, cond, "update", tuple(assignments)
                ))
            elif matched and self.accept_kw("DELETE"):
                clauses.append(ast.MergeClause(True, cond, "delete"))
            elif not matched and self.accept_kw("INSERT"):
                cols = None
                if self.at_op("("):
                    self.next()
                    cols = self._parse_name_list()
                self.expect_kw("VALUES")
                self.expect_op("(")
                vals = [self.parse_expr()]
                while self.accept_op(","):
                    vals.append(self.parse_expr())
                self.expect_op(")")
                clauses.append(ast.MergeClause(
                    False, cond, "insert",
                    insert_columns=cols, insert_values=tuple(vals),
                ))
            else:
                raise self.error(
                    "expected UPDATE/DELETE (matched) or INSERT "
                    "(not matched)"
                )
        if not clauses:
            raise self.error("MERGE requires at least one WHEN clause")
        return ast.Merge(table, target_alias, source, on, tuple(clauses))

    # -- relations --
    def _parse_relation(self) -> ast.Relation:
        left = self._parse_table_primary()
        while True:
            if self.accept_op(","):
                right = self._parse_table_primary()
                left = ast.Join("cross", left, right)
                continue
            kind = None
            if self.at_kw("CROSS"):
                self.next()
                self.expect_kw("JOIN")
                left = ast.Join("cross", left, self._parse_table_primary())
                continue
            if self.at_kw("JOIN"):
                self.next()
                kind = "inner"
            elif self.at_kw("INNER"):
                self.next()
                self.expect_kw("JOIN")
                kind = "inner"
            elif self.at_kw("LEFT", "RIGHT", "FULL"):
                kind = self.next().upper.lower()
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
            else:
                return left
            right = self._parse_table_primary()
            if self.accept_kw("ON"):
                cond = self.parse_expr()
                left = ast.Join(kind, left, right, cond)
            elif self.accept_kw("USING"):
                self.expect_op("(")
                left = ast.Join(kind, left, right, None, self._parse_name_list())
            else:
                raise self.error("expected ON or USING after JOIN")

    def _parse_table_primary(self) -> ast.Relation:
        if (
            self.at_kw("UNNEST")
            and self.peek(1).kind == "op"
            and self.peek(1).text == "("
        ):
            self.next()
            self.expect_op("(")
            arrays = [self.parse_expr()]
            while self.accept_op(","):
                arrays.append(self.parse_expr())
            self.expect_op(")")
            ordinality = False
            if self.accept_kw("WITH"):
                self.expect_kw("ORDINALITY")
                ordinality = True
            alias, cols = self._parse_opt_alias_with_columns()
            return ast.UnnestRelation(tuple(arrays), ordinality, alias, cols)
        if (
            self.at_kw("TABLE")
            and self.peek(1).kind == "op"
            and self.peek(1).text == "("
        ):
            # FROM TABLE(fn(...)) — table-function invocation
            self.next()
            self.expect_op("(")
            name = self._parse_qualified_name()
            self.expect_op("(")
            args: list = []
            named: list = []
            if not self.at_op(")"):
                while True:
                    if (
                        self.peek().kind in ("ident", "qident")
                        and self.peek(1).kind == "op"
                        and self.peek(1).text == "=>"
                    ):
                        pname = self._parse_name()
                        self.next()  # =>
                        named.append((pname, self._parse_tf_arg()))
                    else:
                        args.append(self._parse_tf_arg())
                    if not self.accept_op(","):
                        break
            self.expect_op(")")
            self.expect_op(")")
            alias, cols = self._parse_opt_alias_with_columns()
            return ast.TableFunctionRelation(
                name, tuple(args), tuple(named), alias, cols
            )
        if self.accept_op("("):
            # subquery (incl. inline VALUES) or parenthesized join
            if self.at_kw("SELECT", "WITH", "VALUES"):
                q = self.parse_query()
                self.expect_op(")")
                alias, cols = self._parse_opt_alias_with_columns()
                return ast.SubqueryRelation(q, alias, cols)
            rel = self._parse_relation()
            self.expect_op(")")
            return rel
        name = self._parse_qualified_name()
        if self.at_kw("MATCH_RECOGNIZE"):
            return self._parse_match_recognize(ast.TableRef(name, None))
        alias = self._parse_opt_alias()
        return ast.TableRef(name, alias)

    def _parse_match_recognize(self, input_rel: ast.Relation) -> ast.Relation:
        """MATCH_RECOGNIZE (PARTITION BY ... ORDER BY ... MEASURES ...
        [ONE|ALL] ROW[S] PER MATCH [AFTER MATCH SKIP ...]
        PATTERN (...) DEFINE ...) — SqlBase.g4 patternRecognition."""
        self.expect_kw("MATCH_RECOGNIZE")
        self.expect_op("(")
        partition_by: list = []
        order_by: list = []
        measures: list = []
        rows_per_match = "one"
        after_match = "past_last"
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            partition_by.append(self.parse_expr())
            while self.accept_op(","):
                partition_by.append(self.parse_expr())
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            order_by.append(self._parse_sort_item())
            while self.accept_op(","):
                order_by.append(self._parse_sort_item())
        if self.accept_kw("MEASURES"):
            while True:
                e = self.parse_expr()
                self.expect_kw("AS")
                measures.append(ast.MeasureItem(e, self._parse_name()))
                if not self.accept_op(","):
                    break
        if self.at_kw("ONE", "ALL"):
            rows_per_match = self.next().upper.lower()
            self.accept_kw("ROW") or self.expect_kw("ROWS")
            self.expect_kw("PER")
            self.expect_kw("MATCH")
        if self.accept_kw("AFTER"):
            self.expect_kw("MATCH")
            self.expect_kw("SKIP")
            if self.accept_kw("PAST"):
                self.expect_kw("LAST")
                self.expect_kw("ROW")
                after_match = "past_last"
            elif self.accept_kw("TO"):
                self.expect_kw("NEXT")
                self.expect_kw("ROW")
                after_match = "next_row"
            else:
                raise self.error(
                    "expected PAST LAST ROW or TO NEXT ROW after SKIP"
                )
        self.expect_kw("PATTERN")
        self.expect_op("(")
        pattern = self._parse_pattern_alt()
        self.expect_op(")")
        self.expect_kw("DEFINE")
        defines = []
        while True:
            var = self._parse_name()
            self.expect_kw("AS")
            defines.append((var, self.parse_expr()))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        alias = self._parse_opt_alias()
        return ast.MatchRecognizeRelation(
            input_rel, tuple(partition_by), tuple(order_by),
            tuple(measures), rows_per_match, after_match, pattern,
            tuple(defines), alias,
        )

    def _parse_pattern_alt(self):
        parts = [self._parse_pattern_seq()]
        while self.accept_op("|"):
            parts.append(self._parse_pattern_seq())
        return parts[0] if len(parts) == 1 else ("alt", parts)

    def _parse_pattern_seq(self):
        parts = []
        while not (self.at_op(")") or self.at_op("|")):
            parts.append(self._parse_pattern_quantified())
        if not parts:
            raise self.error("empty pattern")
        return parts[0] if len(parts) == 1 else ("seq", parts)

    def _parse_pattern_quantified(self):
        if self.accept_op("("):
            prim = self._parse_pattern_alt()
            self.expect_op(")")
        else:
            prim = ("var", self._parse_name())
        if self.accept_op("*"):
            return ("star", prim)
        if self.accept_op("+"):
            return ("plus", prim)
        if self.accept_op("?"):
            return ("opt", prim)
        if self.accept_op("{"):
            t = self.next()
            if t.kind != "number":
                raise self.error("expected a number in {n,m} quantifier")
            n = int(t.text)
            m = n
            if self.accept_op(","):
                m = None
                if self.peek().kind == "number":
                    m = int(self.next().text)
            self.expect_op("}")
            return ("rep", prim, n, m)
        return prim

    def _parse_tf_arg(self) -> ast.Expression:
        """One table-function argument: scalar expression, TABLE(rel),
        or DESCRIPTOR(col, ...)."""
        if (
            self.at_kw("TABLE")
            and self.peek(1).kind == "op"
            and self.peek(1).text == "("
        ):
            self.next()
            self.expect_op("(")
            rel = self._parse_relation()
            self.expect_op(")")
            return ast.TableArg(rel)
        if (
            self.at_kw("DESCRIPTOR")
            and self.peek(1).kind == "op"
            and self.peek(1).text == "("
        ):
            self.next()
            self.expect_op("(")
            return ast.Descriptor(self._parse_name_list())
        return self.parse_expr()

    def _parse_opt_alias_with_columns(self):
        """`[AS] alias [(col, ...)]` — derived column aliases."""
        alias = self._parse_opt_alias()
        cols: Tuple[str, ...] = ()
        if alias is not None and self.accept_op("("):
            cols = self._parse_name_list()
        return alias, cols

    def _parse_name_list(self) -> Tuple[str, ...]:
        """Comma-separated identifiers up to and including the closing
        ')' (the opening '(' is already consumed)."""
        names = [self._parse_name()]
        while self.accept_op(","):
            names.append(self._parse_name())
        self.expect_op(")")
        return tuple(names)

    def _parse_opt_alias(self) -> Optional[str]:
        if self.accept_kw("AS"):
            return self._parse_name()
        if self.peek().kind in ("ident", "qident") and self.peek().upper not in _RESERVED_STOP:
            return self._parse_name()
        return None

    def _parse_name(self) -> str:
        t = self.next()
        if t.kind == "qident":
            return t.text[1:-1].replace('""', '"')
        if t.kind != "ident":
            raise ParsingError(f"line {t.line}:{t.col}: expected identifier, found {t.text!r}")
        return t.text.lower()

    def _parse_qualified_name(self) -> Tuple[str, ...]:
        parts = [self._parse_name()]
        while self.at_op(".") and self.peek(1).kind in ("ident", "qident"):
            self.next()
            parts.append(self._parse_name())
        return tuple(parts)

    # -- expressions (Pratt) --
    def parse_expr(self) -> ast.Expression:
        lam = self._try_parse_lambda()
        if lam is not None:
            return lam
        return self._parse_or()

    def _try_parse_lambda(self) -> "Optional[ast.Lambda]":
        """`x -> expr` or `(x, y) -> expr` (LambdaExpression.java);
        only consumed when the arrow is actually present."""
        t = self.peek()
        if t.kind == "ident" and self.peek(1).kind == "op" \
                and self.peek(1).text == "->":
            name = self.next().text
            self.next()  # ->
            return ast.Lambda((name.lower(),), self.parse_expr())
        if t.kind == "op" and t.text == "(":
            # lookahead: ( ident [, ident]* ) ->
            i = 1
            names = []
            while True:
                tk = self.peek(i)
                if tk.kind != "ident":
                    return None
                names.append(tk.text.lower())
                nxt = self.peek(i + 1)
                if nxt.kind == "op" and nxt.text == ",":
                    i += 2
                    continue
                if nxt.kind == "op" and nxt.text == ")":
                    arrow = self.peek(i + 2)
                    if arrow.kind == "op" and arrow.text == "->":
                        for _ in range(i + 3):
                            self.next()
                        return ast.Lambda(tuple(names), self.parse_expr())
                return None
        return None

    def _parse_or(self) -> ast.Expression:
        left = self._parse_and()
        while self.accept_kw("OR"):
            left = ast.BinaryOp("or", left, self._parse_and())
        return left

    def _parse_and(self) -> ast.Expression:
        left = self._parse_not()
        while self.accept_kw("AND"):
            left = ast.BinaryOp("and", left, self._parse_not())
        return left

    def _parse_not(self) -> ast.Expression:
        if self.accept_kw("NOT"):
            return ast.UnaryOp("not", self._parse_not())
        return self._parse_predicate()

    def _parse_predicate(self) -> ast.Expression:
        left = self._parse_additive()
        while True:
            negated = False
            if self.at_kw("NOT"):
                nxt = self.peek(1)
                if nxt.kind == "ident" and nxt.upper in ("BETWEEN", "IN", "LIKE"):
                    self.next()
                    negated = True
                else:
                    break
            if self.accept_kw("BETWEEN"):
                low = self._parse_additive()
                self.expect_kw("AND")
                high = self._parse_additive()
                left = ast.Between(left, low, high, negated)
                continue
            if self.accept_kw("IN"):
                self.expect_op("(")
                if self.at_kw("SELECT", "WITH"):
                    q = self.parse_query()
                    self.expect_op(")")
                    left = ast.InSubquery(left, q, negated)
                else:
                    opts = [self.parse_expr()]
                    while self.accept_op(","):
                        opts.append(self.parse_expr())
                    self.expect_op(")")
                    left = ast.InList(left, tuple(opts), negated)
                continue
            if self.accept_kw("LIKE"):
                pattern = self._parse_additive()
                escape = None
                if self.accept_kw("ESCAPE"):
                    escape = self._parse_additive()
                left = ast.Like(left, pattern, escape, negated)
                continue
            if self.accept_kw("IS"):
                neg = self.accept_kw("NOT")
                if self.accept_kw("NULL"):
                    left = ast.IsNullPredicate(left, neg)
                elif self.accept_kw("DISTINCT"):
                    self.expect_kw("FROM")
                    right = self._parse_additive()
                    eq = ast.BinaryOp("is_distinct", left, right)
                    left = ast.UnaryOp("not", eq) if neg else eq
                else:
                    raise self.error("expected NULL or DISTINCT FROM after IS")
                continue
            if self.peek().kind == "op" and self.peek().text in ("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.next().text
                op = {"=": "eq", "<>": "ne", "!=": "ne", "<": "lt", "<=": "le",
                      ">": "gt", ">=": "ge"}[op]
                right = self._parse_additive()
                left = ast.BinaryOp(op, left, right)
                continue
            break
        return left

    def _parse_additive(self) -> ast.Expression:
        left = self._parse_multiplicative()
        while self.at_op("+", "-", "||"):
            op = self.next().text
            right = self._parse_multiplicative()
            if op == "||":
                left = ast.FunctionCall("concat", (left, right))
            else:
                left = ast.BinaryOp({"+": "add", "-": "sub"}[op], left, right)
        return left

    def _parse_multiplicative(self) -> ast.Expression:
        left = self._parse_unary()
        while self.at_op("*", "/", "%"):
            op = self.next().text
            right = self._parse_unary()
            left = ast.BinaryOp({"*": "mul", "/": "div", "%": "mod"}[op], left, right)
        return left

    def _parse_unary(self) -> ast.Expression:
        if self.accept_op("-"):
            return ast.UnaryOp("negate", self._parse_unary())
        if self.accept_op("+"):
            return self._parse_unary()
        e = self._parse_primary()
        # postfix subscript: array/map element access a[i] / m[k]
        while self.at_op("["):
            self.next()
            idx = self.parse_expr()
            self.expect_op("]")
            e = ast.Subscript(e, idx)
        # postfix AT TIME ZONE 'zone' — binds tighter than * and +
        # (SqlBase.g4 valueExpression lists AT before the arithmetic
        # alternatives), so `ts AT TIME ZONE 'z' + interval` parses.
        # Full three-keyword lookahead: a bare `at` stays usable as an
        # alias/identifier
        while (
            self.at_kw("AT")
            and getattr(self.peek(1), "upper", "") == "TIME"
            and getattr(self.peek(2), "upper", "") == "ZONE"
        ):
            self.next()
            self.next()
            self.next()
            zone = self._parse_primary()
            e = ast.AtTimeZone(e, zone)
        return e

    def _parse_primary(self) -> ast.Expression:
        t = self.peek()
        if t.kind == "op" and t.text == "?":
            # prepared-statement parameter placeholder (tree/Parameter)
            self.next()
            idx = getattr(self, "_param_count", 0)
            self._param_count = idx + 1
            return ast.Parameter(idx)
        if t.kind == "number":
            self.next()
            return ast.NumberLiteral(t.text)
        if t.kind == "string":
            self.next()
            return ast.StringLiteral(t.text[1:-1].replace("''", "'"))
        if t.kind == "op" and t.text == "(":
            self.next()
            if self.at_kw("SELECT", "WITH"):
                q = self.parse_query()
                self.expect_op(")")
                return ast.ScalarSubquery(q)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind in ("ident", "qident"):
            u = t.upper
            if t.kind == "ident" and u in _HARD_RESERVED:
                raise self.error("expected expression")
            if u == "NULL":
                self.next()
                return ast.NullLiteral()
            if u in ("TRUE", "FALSE"):
                self.next()
                return ast.BooleanLiteral(u == "TRUE")
            if u == "DATE" and self.peek(1).kind == "string":
                self.next()
                return ast.DateLiteral(self.next().text[1:-1])
            if u == "TIMESTAMP" and self.peek(1).kind == "string":
                self.next()
                return ast.TimestampLiteral(self.next().text[1:-1])
            if u == "INTERVAL":
                self.next()
                sign = 1
                if self.accept_op("-"):
                    sign = -1
                v = self.next()
                if v.kind != "string":
                    raise self.error("expected interval string")
                unit = self._parse_name()
                return ast.IntervalLiteral(v.text[1:-1], unit.lower(), sign)
            if u == "CASE":
                return self._parse_case()
            if u == "CAST":
                self.next()
                self.expect_op("(")
                operand = self.parse_expr()
                self.expect_kw("AS")
                target = self._parse_type()
                self.expect_op(")")
                return ast.Cast(operand, target)
            if u == "EXISTS" and self.peek(1).kind == "op" and self.peek(1).text == "(":
                self.next()
                self.expect_op("(")
                q = self.parse_query()
                self.expect_op(")")
                return ast.Exists(q)
            if u == "ARRAY" and self.peek(1).kind == "op" and self.peek(1).text == "[":
                self.next()
                self.expect_op("[")
                elements: List[ast.Expression] = []
                if not self.at_op("]"):
                    elements.append(self.parse_expr())
                    while self.accept_op(","):
                        elements.append(self.parse_expr())
                self.expect_op("]")
                return ast.ArrayLiteral(tuple(elements))
            if u == "EXTRACT" and self.peek(1).kind == "op" and self.peek(1).text == "(":
                self.next()
                self.expect_op("(")
                field = self._parse_name()
                self.expect_kw("FROM")
                operand = self.parse_expr()
                self.expect_op(")")
                return ast.Extract(field.lower(), operand)
            # function call?
            if self.peek(1).kind == "op" and self.peek(1).text == "(":
                name = self._parse_name()
                self.expect_op("(")
                if name == "count" and self.at_op("*"):
                    self.next()
                    self.expect_op(")")
                    args = (ast.Star(),)
                    distinct = False
                else:
                    distinct = self.accept_kw("DISTINCT")
                    arglist: List[ast.Expression] = []
                    if not self.at_op(")"):
                        arglist.append(self.parse_expr())
                        while self.accept_op(","):
                            arglist.append(self.parse_expr())
                    self.expect_op(")")
                    args = tuple(arglist)
                if self.at_kw("OVER"):
                    if distinct:
                        raise self.error(
                            "DISTINCT in window aggregates is not supported"
                        )
                    self.next()
                    return ast.WindowCall(name, args, self._parse_window_spec())
                return ast.FunctionCall(name, args, distinct)
            # identifier (possibly qualified)
            return ast.Identifier(self._parse_qualified_name())
        raise self.error("expected expression")

    def _parse_window_spec(self) -> ast.WindowSpec:
        """OVER (PARTITION BY ... ORDER BY ... [ROWS|RANGE frame])
        (SqlBase.g4 windowSpecification). Frames beyond the three
        UNBOUNDED/CURRENT-ROW shapes are rejected at parse time."""
        self.expect_op("(")
        partition: List[ast.Expression] = []
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            partition.append(self.parse_expr())
            while self.accept_op(","):
                partition.append(self.parse_expr())
        order: List[ast.SortItem] = []
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            order.append(self._parse_sort_item())
            while self.accept_op(","):
                order.append(self._parse_sort_item())
        frame = "range" if order else "partition"
        if self.at_kw("ROWS", "RANGE", "GROUPS"):
            unit = self.next().upper
            if unit == "GROUPS":
                raise self.error("GROUPS frames not supported")

            def bound() -> str:
                if self.accept_kw("UNBOUNDED"):
                    if self.accept_kw("PRECEDING"):
                        return "unbounded_preceding"
                    self.expect_kw("FOLLOWING")
                    return "unbounded_following"
                self.expect_kw("CURRENT")
                self.expect_kw("ROW")
                return "current_row"

            if self.accept_kw("BETWEEN"):
                start = bound()
                self.expect_kw("AND")
                end = bound()
            else:
                start, end = bound(), "current_row"
            if start != "unbounded_preceding":
                raise self.error("only UNBOUNDED PRECEDING frame starts supported")
            if end == "unbounded_following":
                frame = "partition"
            else:
                frame = "rows" if unit == "ROWS" else "range"
        self.expect_op(")")
        return ast.WindowSpec(tuple(partition), tuple(order), frame)

    def _parse_case(self) -> ast.Expression:
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expr()
        whens = []
        while self.accept_kw("WHEN"):
            cond = self.parse_expr()
            self.expect_kw("THEN")
            result = self.parse_expr()
            whens.append(ast.WhenClause(cond, result))
        default = None
        if self.accept_kw("ELSE"):
            default = self.parse_expr()
        self.expect_kw("END")
        return ast.Case(operand, tuple(whens), default)

    def _parse_type(self) -> ast.TypeName:
        t = self.next()
        if t.kind != "ident" or t.upper not in _TYPE_NAMES:
            raise ParsingError(f"line {t.line}:{t.col}: unknown type {t.text!r}")
        name = t.upper.lower()
        if name == "int":
            name = "integer"
        if name == "numeric":
            name = "decimal"
        if name == "array":
            # array(T) or array<T>
            close = ">" if self.accept_op("<") else ")"
            if close == ")":
                self.expect_op("(")
            elem = self._parse_type()
            self.expect_op(close)
            return ast.TypeName("array", (), ((None, elem),))
        if name == "map":
            close = ">" if self.accept_op("<") else ")"
            if close == ")":
                self.expect_op("(")
            k = self._parse_type()
            self.expect_op(",")
            v = self._parse_type()
            self.expect_op(close)
            return ast.TypeName("map", (), ((None, k), (None, v)))
        if name == "row":
            self.expect_op("(")
            fields = []
            while True:
                # "name type" or bare "type" (anonymous field)
                fname = None
                if (
                    self.peek().kind == "ident"
                    and self.peek(1).kind == "ident"
                ):
                    fname = self._parse_name()
                fields.append((fname, self._parse_type()))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return ast.TypeName("row", (), tuple(fields))
        params: Tuple[int, ...] = ()
        if name == "double" and self.at_kw("PRECISION"):
            self.next()
        if self.at_op("("):
            self.next()
            ps = [int(self.next().text)]
            while self.accept_op(","):
                ps.append(int(self.next().text))
            self.expect_op(")")
            params = tuple(ps)
        if name == "timestamp" and self.at_kw("WITH"):
            # TIMESTAMP [(p)] WITH TIME ZONE
            self.next()
            self.expect_kw("TIME")
            self.expect_kw("ZONE")
            return ast.TypeName("timestamp with time zone", params)
        return ast.TypeName(name, params)


def parse(sql: str) -> ast.Node:
    return Parser(sql).parse_statement()


def parse_query(sql: str) -> ast.Query:
    node = parse(sql)
    if not isinstance(node, ast.Query):
        raise ParsingError("expected a query")
    return node
