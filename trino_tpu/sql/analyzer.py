"""Analyzer + logical planner: AST -> typed logical plan.

Plays the role of Trino's Analyzer/StatementAnalyzer + LogicalPlanner/
RelationPlanner/QueryPlanner (main/sql/analyzer/StatementAnalyzer.java:391,
main/sql/planner/LogicalPlanner.java:232 — SURVEY.md §2.1/2.2), fused
into one pass: name/type resolution happens while the plan is built, so
expressions come out as channel-indexed typed IR directly.

Capabilities mirrored from the reference that shape this file:
- implicit-join reordering: FROM lists + WHERE equi-conjuncts become a
  greedy hash-join tree with smaller side as build (the stats-lite
  stand-in for the CBO's join ordering, main/cost/).
- subquery planning: EXISTS/NOT EXISTS -> semi/anti joins with residual
  filters; IN (subquery) -> semi/anti joins; scalar subqueries ->
  cross join (uncorrelated) or group-by + left join (correlated equi
  pattern) — the TransformCorrelated* / TransformExistsApplyToCorrelatedJoin
  rule family (main/sql/planner/iterative/rule/).
- aggregation analysis: group keys + aggregate calls pre-projected to
  channels; SELECT/HAVING/ORDER BY rewritten over the aggregate output
  (AggregationAnalyzer analogue).

Known deviations (documented):
- decimal overflow past 38 digits yields NULL rows instead of Trino's
  NUMERIC_VALUE_OUT_OF_RANGE error (same deviation class as
  data-dependent division by zero — a deferred error-flag sideband is
  the planned fix). Int128 division is complete: divisors beyond int64
  run the 128/128 bit-serial kernel (ops/int128.divmod_u128_u128).
Formerly-deviant semantics now implemented faithfully: NULL-aware
NOT IN (filter + anti join + subquery-NULL-count guard), scalar
subqueries yielding NULL on zero rows and raising on >1
(EnforceSingleRowNode), decimal-typed division and avg, and (r4) the
full Trino decimal type algebra — precisions to 38 carried as Int128
limb pairs (ops/int128.py), DecimalOperators result typing for
+,-,*,/,%, sum -> decimal(38,s), HALF_UP rescales.
"""

from __future__ import annotations

import contextvars
import dataclasses
import datetime
import math
from typing import Dict, List, Optional, Sequence, Set, Tuple

from trino_tpu import types as T
from trino_tpu.connectors.spi import CatalogManager
from trino_tpu.expr import ir
from trino_tpu.ops.sort import SortKey
from trino_tpu.sql import ast
from trino_tpu.sql import plan as P

AGG_FUNCS = {"sum", "count", "avg", "min", "max", "any_value", "arbitrary"}
# Composite aggregates lowered onto the primitive (sum/count/min/max)
# machinery by _plan_aggregation: each expands to shared primitive
# accumulators plus a finisher expression over their outputs — the
# moral equivalent of Trino's multi-field accumulator states
# (main/operator/aggregation/, e.g. VarianceState), except the state
# fields ARE primitive aggregates so partial->final distribution and
# spill ride the existing wire format unchanged.
COMPOSITE_AGG_FUNCS = {
    "stddev", "stddev_samp", "stddev_pop",
    "variance", "var_samp", "var_pop",
    "skewness", "kurtosis",
    "geometric_mean", "count_if", "bool_and", "bool_or", "every",
    "corr", "covar_pop", "covar_samp", "regr_slope", "regr_intercept",
    # r4 breadth: the full regression family (DoubleRegressionAggregation)
    # plus entropy/checksum — all derivable from the same moment sums
    "regr_avgx", "regr_avgy", "regr_count", "regr_r2",
    "regr_sxx", "regr_sxy", "regr_syy",
    "entropy", "checksum",
}
# Holistic aggregates: need the raw rows (order statistics), so the
# fragmenter runs them single-step after a gather and the operator
# takes its collect path. Single source of truth for the kind set:
# exec/operators.HOLISTIC_KINDS (fragmenter gates on it too).
from trino_tpu.exec.operators import HOLISTIC_KINDS as _HOLISTIC_KINDS

HOLISTIC_AGG_FUNCS = set(_HOLISTIC_KINDS) | {"string_agg", "merge"}
AGG_FUNCS = AGG_FUNCS | COMPOSITE_AGG_FUNCS | HOLISTIC_AGG_FUNCS

_EPOCH = datetime.date(1970, 1, 1)


class AnalysisError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Scopes
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ScopeField:
    qualifier: Optional[str]
    name: Optional[str]
    type: T.DataType


class Scope:
    """Channel-aligned name table for one plan node's output."""

    def __init__(self, fields: Sequence[ScopeField]):
        self.fields = list(fields)

    def __len__(self):
        return len(self.fields)

    def try_resolve(self, parts: Tuple[str, ...]) -> Optional[Tuple[int, T.DataType]]:
        if len(parts) == 1:
            qualifier, name = None, parts[0]
        elif len(parts) == 2:
            qualifier, name = parts
        else:
            return None
        hits = [
            (i, f.type)
            for i, f in enumerate(self.fields)
            if f.name == name and (qualifier is None or f.qualifier == qualifier)
        ]
        if len(hits) > 1:
            raise AnalysisError(f"column '{'.'.join(parts)}' is ambiguous")
        return hits[0] if hits else None

    def resolve(self, parts: Tuple[str, ...]) -> Tuple[int, T.DataType]:
        hit = self.try_resolve(parts)
        if hit is None:
            raise AnalysisError(f"column '{'.'.join(parts)}' cannot be resolved")
        return hit

    @staticmethod
    def concat(a: "Scope", b: "Scope") -> "Scope":
        return Scope(a.fields + b.fields)


def _plan_fields(scope: Scope) -> Tuple[P.Field, ...]:
    return tuple(P.Field(f.name, f.type) for f in scope.fields)


# ---------------------------------------------------------------------------
# Expression conversion
# ---------------------------------------------------------------------------


def _number_literal(text: str) -> ir.Literal:
    if "e" in text.lower():
        return ir.Literal(float(text), T.DOUBLE)
    if "." in text:
        frac = text.split(".")[1]
        scale = len(frac)
        digits = len(text.replace(".", "").lstrip("0")) or 1
        if digits > 15:
            # float would corrupt digits beyond ~2^53; carry the exact
            # value (scale_decimal_value handles Decimal exactly)
            import decimal as _d

            return ir.Literal(
                _d.Decimal(text),
                T.decimal(min(max(digits, scale + 1), 38), scale),
            )
        return ir.Literal(float(text), T.decimal(max(digits, scale + 1), scale))
    v = int(text)
    if abs(v) > 2 ** 63 - 1:
        # beyond BIGINT: an exact decimal literal (Trino types big
        # integer literals DECIMAL(n, 0))
        return ir.Literal(v, T.decimal(min(len(str(abs(v))), 38), 0))
    return ir.Literal(v, T.BIGINT)


def _date_days(value: str) -> int:
    y, m, d = map(int, value.split("-"))
    return (datetime.date(y, m, d) - _EPOCH).days


def _shift_date(days: int, unit: str, n: int) -> int:
    d = _EPOCH + datetime.timedelta(days=days)
    if unit == "day":
        return days + n
    if unit == "month":
        m = d.month - 1 + n
        y = d.year + m // 12
        m = m % 12 + 1
        import calendar

        day = min(d.day, calendar.monthrange(y, m)[1])
        return (datetime.date(y, m, day) - _EPOCH).days
    if unit == "year":
        return _shift_date(days, "month", 12 * n)
    raise AnalysisError(f"unsupported interval unit {unit}")


def _unify_types(types: Sequence[T.DataType]) -> T.DataType:
    types = [t for t in types if t.kind != T.TypeKind.UNKNOWN]
    if not types:
        return T.UNKNOWN
    if any(t.is_string for t in types):
        return T.VARCHAR
    if any(t.is_floating for t in types):
        return T.DOUBLE
    if any(t.is_decimal for t in types):
        scale = max((t.scale or 0) for t in types if t.is_decimal)
        intd = max(
            (T._as_decimal_shape(t)[0] - T._as_decimal_shape(t)[1])
            for t in types
            if t.is_numeric
        )
        return T.decimal(min(intd + scale, T.MAX_DECIMAL_PRECISION), scale)
    if any(t.kind == T.TypeKind.DATE for t in types):
        return T.DATE
    if any(t.kind == T.TypeKind.BOOLEAN for t in types):
        return T.BOOLEAN
    return T.BIGINT


# Per-query session time zone (Session.timezone), read by literal
# parsing and zone-dependent cast rewrites. A contextvar keeps
# concurrent server queries isolated (Session.java getTimeZoneKey).
_SESSION_ZONE = contextvars.ContextVar("trino_tpu_session_zone", default="UTC")

# set when analysis folds a VOLATILE value (now()/current_date/...)
# into the plan — such plans must not enter the SQL-text plan cache
# (a cached `select now()` would return its first timestamp forever)
_VOLATILE_PLAN = contextvars.ContextVar("trino_tpu_volatile_plan", default=False)


def session_zone() -> str:
    return _SESSION_ZONE.get()


def set_session_zone(zone: str) -> None:
    _SESSION_ZONE.set(zone)


# catalog/schema/user for the parenless session pseudo-columns
# (CURRENT_CATALOG / CURRENT_SCHEMA / CURRENT_USER)
_SESSION_INFO = contextvars.ContextVar(
    "trino_tpu_session_info", default=("", "", "user")
)


def set_session_info(catalog: str, schema: str, user: str) -> None:
    _SESSION_INFO.set((catalog, schema, user))


def reset_volatile_plan() -> None:
    _VOLATILE_PLAN.set(False)


def mark_volatile_plan() -> None:
    _VOLATILE_PLAN.set(True)


def plan_is_volatile() -> bool:
    return _VOLATILE_PLAN.get()


# functions whose tstz argument reads the LOCAL wall clock in the
# value's own zone (extract-family + formatting; DateTimes.java)
_TSTZ_WALL_FNS = {
    "year", "month", "day", "hour", "minute", "second", "millisecond",
    "quarter", "week", "dow", "doy", "day_of_week", "day_of_year",
    "day_of_month", "year_of_week", "yow", "format_datetime",
    "date_format", "last_day_of_month", "to_iso8601",
}


def _arith_type(op: str, lt: T.DataType, rt: T.DataType) -> T.DataType:
    if lt.kind == T.TypeKind.DATE or rt.kind == T.TypeKind.DATE:
        return T.DATE
    if lt.is_floating or rt.is_floating:
        return T.DOUBLE
    if lt.is_decimal or rt.is_decimal:
        # Trino's exact decimal operator typing incl. Int128 results
        # (main/type/DecimalOperators.java longVariables)
        return T.decimal_arith_type(op, lt, rt)
    return T.BIGINT


class ExprConverter:
    """AST expression -> typed IR over one scope, honoring replacement
    channels installed by aggregation/subquery planning."""

    def __init__(
        self,
        scope: Scope,
        replacements: Optional[Dict[ast.Expression, Tuple[int, T.DataType]]] = None,
    ):
        self.scope = scope
        self.replacements = replacements or {}

    def convert(self, e: ast.Expression) -> ir.Expr:
        if e in self.replacements:
            ch, t = self.replacements[e]
            return ir.InputRef(ch, t)
        if isinstance(e, ast.Identifier):
            lam_scope = getattr(self, "_lambda_scope", None)
            if lam_scope and len(e.parts) == 1:
                lv = lam_scope.get(e.parts[0].lower())
                if lv is not None:
                    return lv
            hit = self.scope.try_resolve(e.parts)
            if hit is None and len(e.parts) >= 2:
                # ROW field access: resolve the prefix as a row-typed
                # column, the last part as its field (RowType dereference,
                # spi/type/RowType field access)
                base = self.scope.try_resolve(e.parts[:-1])
                if base is not None and base[1].is_row:
                    ch, rt = base
                    fname = e.parts[-1].lower()
                    for fi, (n, ft) in enumerate(rt.row_fields):
                        if n is not None and n.lower() == fname:
                            return ir.Call(
                                "row_field",
                                (ir.InputRef(ch, rt),
                                 ir.Literal(fi, T.BIGINT)),
                                ft,
                            )
                    raise AnalysisError(
                        f"row type has no field {e.parts[-1]!r}"
                    )
            if hit is None and len(e.parts) == 1:
                special = self._zero_arg_special(e.parts[0].lower())
                if special is not None:
                    return special
            ch, t = self.scope.resolve(e.parts)
            return ir.InputRef(ch, t)
        if isinstance(e, ast.Subscript):
            return self._convert_subscript(e)
        if isinstance(e, ast.NumberLiteral):
            return _number_literal(e.text)
        if isinstance(e, ast.StringLiteral):
            return ir.Literal(e.value, T.VARCHAR)
        if isinstance(e, ast.BooleanLiteral):
            return ir.Literal(e.value, T.BOOLEAN)
        if isinstance(e, ast.NullLiteral):
            return ir.Literal(None, T.UNKNOWN)
        if isinstance(e, ast.DateLiteral):
            return ir.Literal(_date_days(e.value), T.DATE)
        if isinstance(e, ast.TimestampLiteral):
            from trino_tpu.expr.pyfns import iso_to_micros
            from trino_tpu.ops import tz as TZ

            # a trailing zone name/offset makes the literal a TIMESTAMP
            # WITH TIME ZONE (parser/sql/tree/TimestampLiteral + the
            # DateTimes.java literal parse)
            if TZ.literal_has_zone(e.value):
                packed = TZ.parse_tstz(e.value, session_zone())
                if packed is None:
                    raise AnalysisError(f"invalid timestamp: {e.value!r}")
                return ir.Literal(packed, T.TIMESTAMP_TZ)
            micros = iso_to_micros(e.value)
            if micros is None:
                raise AnalysisError(f"invalid timestamp: {e.value!r}")
            return ir.Literal(micros, T.TIMESTAMP)
        if isinstance(e, ast.AtTimeZone):
            return self._convert_at_timezone(e)
        if isinstance(e, ast.IntervalLiteral):
            raise AnalysisError("intervals are only supported in date arithmetic")
        if isinstance(e, ast.BinaryOp):
            return self._convert_binary(e)
        if isinstance(e, ast.UnaryOp):
            if e.op == "not":
                return ir.not_(self.convert(e.operand))
            if e.op == "negate":
                a = self.convert(e.operand)
                if isinstance(a, ir.Literal) and a.value is not None:
                    return ir.Literal(-a.value, a.type)
                return ir.Call("negate", (a,), a.type)
        if isinstance(e, ast.IsNullPredicate):
            x = ir.is_null(self.convert(e.operand))
            return ir.not_(x) if e.negated else x
        if isinstance(e, ast.Between):
            v = self.convert(e.value)
            lo = self.convert(e.low)
            hi = self.convert(e.high)
            v1, lo = self._coerce_temporal_pair(v, lo)
            v2, hi = self._coerce_temporal_pair(v1, hi)
            x = ir.and_(
                ir.comparison("ge", v2, lo), ir.comparison("le", v2, hi)
            )
            return ir.not_(x) if e.negated else x
        if isinstance(e, ast.InList):
            v = self.convert(e.value)
            opts = []
            for o in e.options:
                lit = self.convert(o)
                if not isinstance(lit, ir.Literal):
                    raise AnalysisError("IN list items must be literals")
                opts.append(lit)
            # temporal coercion over the WHOLE list at once: lifting v
            # mid-loop would leave earlier options un-lifted
            TSTZ_K = T.TypeKind.TIMESTAMP_TZ
            if v.type.kind == TSTZ_K or any(
                o.type.kind == TSTZ_K for o in opts
            ):
                coerced = []
                for lit in opts:
                    v, lit = self._coerce_temporal_pair(v, lit)
                    coerced.append(lit)
                opts = []
                for lit in coerced:
                    v, lit = self._coerce_temporal_pair(v, lit)
                    if not isinstance(lit, ir.Literal):
                        raise AnalysisError(
                            "IN list items must be literals"
                        )
                    opts.append(lit)
            x: ir.Expr = ir.InList(v, tuple(opts))
            return ir.not_(x) if e.negated else x
        if isinstance(e, ast.Like):
            v = self.convert(e.value)
            pat = self.convert(e.pattern)
            if not isinstance(pat, ir.Literal):
                raise AnalysisError("LIKE pattern must be a literal")
            args = [v, pat]
            if e.escape is not None:
                esc = self.convert(e.escape)
                args.append(esc)
            x = ir.Call("like", tuple(args), T.BOOLEAN)
            return ir.not_(x) if e.negated else x
        if isinstance(e, ast.Case):
            return self._convert_case(e)
        if isinstance(e, ast.Cast):
            return self._convert_cast(e)
        if isinstance(e, ast.Extract):
            a = self.convert(e.operand)
            if a.type.kind == T.TypeKind.TIMESTAMP_TZ:
                if e.field in ("timezone_hour", "timezone_minute"):
                    return ir.Call(f"tstz_{e.field}", (a,), T.BIGINT)
                # civil fields read the LOCAL wall clock in the value's
                # own zone (DateTimes.java extract semantics)
                a = ir.Call("tstz_to_ts", (a,), T.TIMESTAMP)
            if e.field in ("year", "month", "day"):
                return ir.Call(f"extract_{e.field}", (a,), T.BIGINT)
            if e.field in ("hour", "minute", "second"):
                # time-of-day fields need a timestamp operand (Trino
                # rejects DATE here with a type error)
                if a.type.kind != T.TypeKind.TIMESTAMP:
                    raise AnalysisError(
                        f"cannot extract {e.field} from {a.type}"
                    )
                return ir.Call(e.field, (a,), T.BIGINT)
            canon = {"quarter": "quarter", "week": "week",
                     "dow": "day_of_week", "day_of_week": "day_of_week",
                     "doy": "day_of_year", "day_of_year": "day_of_year"}
            if e.field in canon:
                return ir.Call(canon[e.field], (a,), T.BIGINT)
            raise AnalysisError(f"extract({e.field}) not supported")
        if isinstance(e, ast.FunctionCall):
            return self._convert_call(e)
        if isinstance(e, ast.Lambda):
            raise AnalysisError(
                "lambda expressions are only valid as higher-order "
                "function arguments (transform, filter, ...)"
            )
        if isinstance(e, ast.ArrayLiteral):
            vals = _const_array_values(e)
            if vals is None:
                raise AnalysisError(
                    "ARRAY[...] literals must contain constants"
                )
            elems = [self.convert(x) for x in e.elements]
            elem_t = _unify_types([x.type for x in elems]) if elems else T.BIGINT
            return ir.Literal(
                tuple(x.value for x in elems), T.array_of(elem_t)
            )
        if isinstance(e, (ast.Exists, ast.InSubquery)):
            # mark-join replacements register under the non-negated twin
            plain = dataclasses.replace(e, negated=False)
            hit = self.replacements.get(plain)
            if hit is not None:
                x: ir.Expr = ir.InputRef(hit[0], T.BOOLEAN)
                return ir.not_(x) if e.negated else x
        if isinstance(e, (ast.Exists, ast.InSubquery, ast.ScalarSubquery)):
            raise AnalysisError(
                "subquery in unsupported position (only WHERE/HAVING conjuncts)"
            )
        raise AnalysisError(f"cannot analyze expression {e!r}")

    # -- binary --
    def _convert_binary(self, e: ast.BinaryOp) -> ir.Expr:
        op = e.op
        if op in ("and", "or"):
            return ir.Call(op, (self.convert(e.left), self.convert(e.right)), T.BOOLEAN)
        if op in ("eq", "ne", "lt", "le", "gt", "ge"):
            l, r = self._coerce_temporal_pair(
                self.convert(e.left), self.convert(e.right)
            )
            return ir.comparison(op, l, r)
        if op == "is_distinct":
            l, r = self._coerce_temporal_pair(
                self.convert(e.left), self.convert(e.right)
            )
            # NOT ((a=b, null-safe false) OR (a NULL AND b NULL)) — the
            # eq lane must be made definite (coalesce) so the result is
            # never NULL, matching Trino's IS DISTINCT FROM
            eq_definite = ir.Call(
                "coalesce",
                (ir.comparison("eq", l, r), ir.Literal(False, T.BOOLEAN)),
                T.BOOLEAN,
            )
            same = ir.or_(eq_definite, ir.and_(ir.is_null(l), ir.is_null(r)))
            return ir.not_(same)
        if op in ("add", "sub", "mul", "div", "mod"):
            # date +- interval
            if isinstance(e.right, ast.IntervalLiteral) and op in ("add", "sub"):
                return self._date_interval(e.left, e.right, op)
            l = self.convert(e.left)
            r = self.convert(e.right)
            out_t = _arith_type(op, l.type, r.type)
            return ir.Call(op, (l, r), out_t)
        raise AnalysisError(f"operator {op} not supported")

    def _date_interval(self, date_ast, interval: ast.IntervalLiteral, op) -> ir.Expr:
        n = int(interval.value) * interval.sign * (1 if op == "add" else -1)
        d = self.convert(date_ast)
        if isinstance(d, ir.Literal) and d.type.kind == T.TypeKind.DATE:
            return ir.Literal(_shift_date(d.value, interval.unit, n), T.DATE)
        if d.type.kind == T.TypeKind.TIMESTAMP_TZ:
            # fixed-duration shift on the INSTANT (zone bits untouched;
            # Trino adds exact millis for day-second intervals)
            unit_ms = {
                "day": 86_400_000, "hour": 3_600_000,
                "minute": 60_000, "second": 1_000,
            }.get(interval.unit)
            if unit_ms is None:
                raise AnalysisError(
                    "month/year intervals on timestamp with time zone "
                    "are not supported"
                )
            return ir.Call(
                "tstz_shift",
                (d, ir.Literal(n * unit_ms, T.BIGINT)),
                T.TIMESTAMP_TZ,
            )
        if interval.unit == "day":
            return ir.Call("add", (d, ir.Literal(n, T.DATE)), T.DATE)
        raise AnalysisError(
            "month/year interval arithmetic requires a constant date operand"
        )

    def _convert_case(self, e: ast.Case) -> ir.Expr:
        whens = list(e.whens)
        if e.operand is not None:
            conds = [
                self.convert(ast.BinaryOp("eq", e.operand, w.condition)) for w in whens
            ]
        else:
            conds = [self.convert(w.condition) for w in whens]
        results = [self.convert(w.result) for w in whens]
        default = self.convert(e.default) if e.default is not None else None
        out_t = _unify_types(
            [r.type for r in results] + ([default.type] if default is not None else [])
        )
        return ir.Case(tuple(conds), tuple(results), default, out_t)

    def _convert_cast(self, e: ast.Cast) -> ir.Expr:
        a = self.convert(e.operand)
        return self._cast_to(a, resolve_type(e.target))

    def _cast_to(self, a: ir.Expr, dst: T.DataType) -> ir.Expr:
        """Casts involving TIMESTAMP WITH TIME ZONE rewrite into calls
        carrying the session zone as a literal (the zone must be fixed
        at ANALYSIS time — Session.getTimeZoneKey — because bound
        expressions run on workers with no session)."""
        src = a.type
        TSTZ = T.TypeKind.TIMESTAMP_TZ
        if dst.kind == TSTZ and src.kind != TSTZ:
            from trino_tpu.ops import tz as TZ

            sz = ir.Literal(TZ.zone_id(session_zone()), T.INTEGER)
            if src.kind == T.TypeKind.TIMESTAMP:
                return ir.Call("ts_to_tstz", (a, sz), T.TIMESTAMP_TZ)
            if src.kind == T.TypeKind.DATE:
                ts = ir.Cast(a, T.TIMESTAMP)
                return ir.Call("ts_to_tstz", (ts, sz), T.TIMESTAMP_TZ)
            if src.is_string or src.kind == T.TypeKind.UNKNOWN:
                return ir.Call("parse_tstz", (a, sz), T.TIMESTAMP_TZ)
            raise AnalysisError(
                f"cannot cast {src} to timestamp with time zone"
            )
        if src.kind == TSTZ and dst.kind != TSTZ:
            if dst.kind == T.TypeKind.TIMESTAMP:
                return ir.Call("tstz_to_ts", (a,), T.TIMESTAMP)
            if dst.kind == T.TypeKind.DATE:
                return ir.Cast(
                    ir.Call("tstz_to_ts", (a,), T.TIMESTAMP), T.DATE
                )
            if dst.is_string:
                # constant folding in the binder (_format_cast_text);
                # column-valued follows the timestamp->varchar limit
                return ir.Cast(a, dst)
            raise AnalysisError(
                f"cannot cast timestamp with time zone to {dst}"
            )
        return ir.Cast(a, dst)

    def _coerce_temporal_pair(self, l: ir.Expr, r: ir.Expr):
        """Mixed TIMESTAMP/DATE vs TIMESTAMP WITH TIME ZONE comparison:
        the zone-less side coerces to tstz at the session zone (the
        implicit coercion Trino's type system inserts) — raw int64
        compare of micros against the packed encoding would be silent
        garbage."""
        TSTZ = T.TypeKind.TIMESTAMP_TZ
        plain = (T.TypeKind.TIMESTAMP, T.TypeKind.DATE)

        def lift(x: ir.Expr) -> ir.Expr:
            if isinstance(x, ir.Literal):
                # fold at analysis time so IN-list items stay literals
                if x.value is None:
                    return ir.Literal(None, T.TIMESTAMP_TZ)
                from trino_tpu.ops import tz as TZ

                micros = int(x.value)
                if x.type.kind == T.TypeKind.DATE:
                    micros = micros * 86_400_000_000
                zid = TZ.zone_id(session_zone())
                wall_ms = micros // 1000
                off1 = TZ.offset_millis_py(zid, wall_ms)
                off2 = TZ.offset_millis_py(zid, wall_ms - off1)
                return ir.Literal(
                    TZ.pack_py(wall_ms - off2, zid), T.TIMESTAMP_TZ
                )
            if x.type.kind == T.TypeKind.DATE:
                x = ir.Cast(x, T.TIMESTAMP)
            return self._cast_to(x, T.TIMESTAMP_TZ)

        if l.type.kind == TSTZ and r.type.kind in plain:
            return l, lift(r)
        if r.type.kind == TSTZ and l.type.kind in plain:
            return lift(l), r
        return l, r

    def _zero_arg_special(self, name: str) -> Optional[ir.Expr]:
        """Parenless standard temporal pseudo-columns (SqlBase.g4
        specialDateTimeFunction): CURRENT_TIMESTAMP / CURRENT_DATE /
        LOCALTIMESTAMP / CURRENT_TIMEZONE, all at the session zone."""
        import time as _time

        from trino_tpu.ops import tz as TZ

        if name == "current_timestamp":
            mark_volatile_plan()
            return ir.Literal(
                TZ.pack_py(
                    int(_time.time() * 1000), TZ.zone_id(session_zone())
                ),
                T.TIMESTAMP_TZ,
            )
        if name in ("current_date", "localtimestamp"):
            mark_volatile_plan()
            zid = TZ.zone_id(session_zone())
            now_ms = int(_time.time() * 1000)
            wall_ms = now_ms + TZ.offset_millis_py(zid, now_ms)
            if name == "localtimestamp":
                return ir.Literal(wall_ms * 1000, T.TIMESTAMP)
            return ir.Literal(wall_ms // 86_400_000, T.DATE)
        if name == "current_timezone":
            mark_volatile_plan()
            return ir.Literal(session_zone(), T.VARCHAR)
        if name in ("current_catalog", "current_schema", "current_user"):
            # session-dependent folds: the plan cache key carries no
            # identity/zone, so these plans must not be cached
            mark_volatile_plan()
            cat, sch, usr = _SESSION_INFO.get()
            v = {"current_catalog": cat, "current_schema": sch,
                 "current_user": usr}[name]
            return ir.Literal(v, T.VARCHAR)
        return None

    def _convert_at_timezone(self, e: "ast.AtTimeZone") -> ir.Expr:
        from trino_tpu.ops import tz as TZ

        a = self.convert(e.operand)
        z = self.convert(e.zone)
        if not (
            isinstance(z, ir.Literal) and z.type.is_string
            and z.value is not None
        ):
            raise AnalysisError("AT TIME ZONE requires a constant zone")
        try:
            zid = TZ.zone_id(str(z.value))
        except ValueError as ex:
            raise AnalysisError(str(ex))
        if a.type.kind == T.TypeKind.TIMESTAMP:
            a = self._cast_to(a, T.TIMESTAMP_TZ)
        if a.type.kind != T.TypeKind.TIMESTAMP_TZ:
            raise AnalysisError("AT TIME ZONE requires a timestamp operand")
        return ir.Call(
            "at_timezone_id", (a, ir.Literal(zid, T.INTEGER)), T.TIMESTAMP_TZ
        )

    # higher-order (lambda-taking) functions: (collection positions,
    # lambda position, param-type derivation) — ArrayFunctions /
    # MapTransformValuesFunction analogues
    _LAMBDA_FUNCS = {
        "transform", "filter", "any_match", "all_match", "none_match",
        "transform_values", "transform_keys", "map_filter",
    }

    def _convert_breadth_call(self, name, e) -> Optional[ir.Expr]:
        """r4 breadth: session-fixed zero-arg functions, cast shorthands,
        desugarings, and constant folds for string-producing functions of
        non-string inputs (the engine's varchar columns are dictionary
        codes, so a per-row numeric->string projection has no vectorized
        carrier; constants fold here, columns get a clean AnalysisError).
        Reference seats: DateTimeFunctions.java (now/current_timezone),
        MathFunctions.java (to_base/random), ColorFunctions.java,
        StringFunctions.java:162 (concat_ws)."""
        import datetime as _dt

        def _arity(lo, hi=None):
            n = len(e.args)
            hi_ = lo if hi is None else hi
            if not lo <= n <= hi_:
                want = str(lo) if hi_ == lo else f"{lo}..{hi_}"
                raise AnalysisError(
                    f"{name}() expects {want} arguments, got {n}"
                )

        def _need_const(args, which=None):
            vals = []
            for i, a in enumerate(args):
                c = self.convert(a)
                if which is not None and i not in which:
                    vals.append(c)
                    continue
                if not isinstance(c, ir.Literal):
                    raise AnalysisError(
                        f"{name}(): argument {i + 1} must be a constant"
                        " (column-valued inputs have no varchar carrier)"
                    )
                vals.append(c)
            return vals

        if name == "now":
            import time as _time

            from trino_tpu.ops import tz as TZ

            if e.args:
                raise AnalysisError("now() takes no arguments")
            # now()/current_timestamp: TIMESTAMP WITH TIME ZONE at the
            # session zone (DateTimeFunctions.java currentTimestamp)
            mark_volatile_plan()
            return ir.Literal(
                TZ.pack_py(
                    int(_time.time() * 1000), TZ.zone_id(session_zone())
                ),
                T.TIMESTAMP_TZ,
            )
        if name == "current_timezone":
            return ir.Literal(session_zone(), T.VARCHAR)
        if name in ("with_timezone", "at_timezone"):
            from trino_tpu.ops import tz as TZ

            if len(e.args) != 2:
                raise AnalysisError(f"{name}() takes two arguments")
            a = self.convert(e.args[0])
            z = self.convert(e.args[1])
            if not (isinstance(z, ir.Literal) and z.value is not None):
                raise AnalysisError(f"{name}() zone must be a constant")
            try:
                zid = TZ.zone_id(str(z.value))
            except ValueError as ex:
                raise AnalysisError(str(ex))
            if name == "with_timezone":
                # wall time reinterpreted IN the given zone
                if a.type.kind != T.TypeKind.TIMESTAMP:
                    raise AnalysisError("with_timezone() takes a timestamp")
                return ir.Call(
                    "ts_to_tstz", (a, ir.Literal(zid, T.INTEGER)),
                    T.TIMESTAMP_TZ,
                )
            # at_timezone: same instant, displayed in the given zone
            if a.type.kind == T.TypeKind.TIMESTAMP:
                a = self._cast_to(a, T.TIMESTAMP_TZ)
            if a.type.kind != T.TypeKind.TIMESTAMP_TZ:
                raise AnalysisError("at_timezone() takes a timestamp")
            return ir.Call(
                "at_timezone_id", (a, ir.Literal(zid, T.INTEGER)),
                T.TIMESTAMP_TZ,
            )
        if name == "uuid":
            import uuid as _uuid

            mark_volatile_plan()
            return ir.Literal(str(_uuid.uuid4()), T.VARCHAR)
        if name == "version":
            return ir.Literal("trino_tpu 0.4", T.VARCHAR)
        if name == "date":
            if len(e.args) != 1:
                raise AnalysisError("date() takes one argument")
            a = self.convert(e.args[0])
            if isinstance(a, ir.Literal) and a.type.is_string:
                if a.value is None:
                    return ir.Literal(None, T.DATE)
                try:
                    return ir.Literal(_date_days(str(a.value)), T.DATE)
                except ValueError:
                    raise AnalysisError(f"invalid date: {a.value!r}")
            return ir.Cast(a, T.DATE)
        if name in ("rand", "random"):
            args = tuple(self.convert(a) for a in e.args)
            if len(args) > 2:
                raise AnalysisError("rand() takes at most two arguments")
            return ir.Call(
                "rand", args, T.DOUBLE if not args else T.BIGINT
            )
        if name in ("regexp_split", "regexp_extract_all"):
            # validate the constant pattern/group at ANALYSIS time and
            # fall through to the registry for typing (the from_base
            # discipline: no raw re.error/IndexError mid-bind)
            import re as _re

            if len(e.args) >= 2:
                pat = self.convert(e.args[1])
                if isinstance(pat, ir.Literal) and pat.value is not None:
                    try:
                        rx = _re.compile(str(pat.value))
                    except _re.error as ex:
                        raise AnalysisError(f"{name}(): invalid pattern"
                                            f" ({ex})")
                    if name == "regexp_extract_all" and len(e.args) > 2:
                        gl = self.convert(e.args[2])
                        if isinstance(gl, ir.Literal) and \
                                gl.value is not None and \
                                not 0 <= int(gl.value) <= rx.groups:
                            raise AnalysisError(
                                f"{name}(): pattern has {rx.groups}"
                                f" groups, got group {gl.value}"
                            )
            return None
        if name == "from_base":
            # validate the constant radix HERE (analysis time) and fall
            # through to the registry for typing — the binder twin's
            # check would surface as a raw ValueError mid-execution
            if len(e.args) == 2:
                r = self.convert(e.args[1])
                if isinstance(r, ir.Literal) and r.value is not None \
                        and not 2 <= int(r.value) <= 36:
                    raise AnalysisError(
                        "from_base() radix must be in [2, 36]"
                    )
            return None
        if name in ("reverse", "concat") and e.args:
            # array overloads fold for constant arrays; non-array
            # arguments fall through to the varchar paths below
            arrs = [_const_array_values(a) for a in e.args]
            if arrs[0] is not None and (name == "reverse" or all(
                x is not None for x in arrs
            )):
                if name == "reverse":
                    if len(e.args) != 1:
                        return None
                    vals = [v.value for v in arrs[0]]
                    t = _array_element_type(arrs[0])
                    return ir.Literal(tuple(reversed(vals)), T.array_of(t))
                # unify element types ACROSS arguments: mixed-type
                # concat must fail at analysis, not corrupt the literal
                flat = [v for xs in arrs for v in xs]
                t = _array_element_type(flat) if flat else T.BIGINT
                return ir.Literal(
                    tuple(v.value for v in flat), T.array_of(t)
                )
            return None
        if name in ("date_format", "to_char", "format_datetime"):
            # constant fold only: per-row timestamp->string projection
            # has no varchar carrier (same rule as to_iso8601)
            import datetime as _dt

            if len(e.args) != 2:
                raise AnalysisError(f"{name}() takes two arguments")
            vals = _need_const(e.args)
            a, fmt = vals
            if a.value is None or fmt.value is None:
                return ir.Literal(None, T.VARCHAR)
            if a.type.kind == T.TypeKind.DATE:
                dt = _dt.datetime(1970, 1, 1) + _dt.timedelta(
                    days=int(a.value)
                )
            elif a.type.kind == T.TypeKind.TIMESTAMP:
                dt = _dt.datetime(1970, 1, 1) + _dt.timedelta(
                    microseconds=int(a.value)
                )
            elif a.type.kind == T.TypeKind.TIMESTAMP_TZ:
                # format the LOCAL wall clock in the value's own zone
                from trino_tpu.ops import tz as TZ

                ms = int(a.value) >> TZ.MILLIS_SHIFT
                off = TZ.offset_millis_py(
                    int(a.value) & TZ.ZONE_MASK, ms
                )
                dt = _dt.datetime(1970, 1, 1) + _dt.timedelta(
                    milliseconds=ms + off
                )
            else:
                raise AnalysisError(f"{name}() takes a date or timestamp")
            if name == "date_format":
                # MySQL tokens (date_parse's inverse). ONLY the tokens
                # that map 1:1 onto strftime are accepted — %M/%W/%c and
                # friends mean different things in MySQL and strftime,
                # so passing them through would silently format wrong
                ok = {"Y": "%Y", "y": "%y", "m": "%m", "d": "%d",
                      "H": "%H", "h": "%I", "i": "%M", "s": "%S",
                      "p": "%p", "j": "%j", "a": "%a", "b": "%b",
                      "%": "%%"}
                src, out, i = str(fmt.value), [], 0
                while i < len(src):
                    if src[i] == "%":
                        tok = src[i + 1] if i + 1 < len(src) else ""
                        if tok not in ok:
                            raise AnalysisError(
                                f"date_format(): unsupported token %{tok}"
                            )
                        out.append(ok[tok])
                        i += 2
                    else:
                        out.append(src[i])
                        i += 1
                py = "".join(out)
            elif name == "format_datetime":
                from trino_tpu.expr.pyfns import joda_to_strptime

                py = joda_to_strptime(str(fmt.value))
            else:
                from trino_tpu.expr.pyfns import oracle_to_strptime

                py = oracle_to_strptime(str(fmt.value))
            return ir.Literal(dt.strftime(py), T.VARCHAR)
        if name == "empty_approx_set":
            from trino_tpu.expr.pyfns import hll_merge

            if e.args:
                raise AnalysisError("empty_approx_set() takes no arguments")
            return ir.Literal(hll_merge([]), T.VARCHAR)
        if name == "format":
            if len(e.args) < 2:
                raise AnalysisError("format() needs a format + values")
            vals = _need_const(e.args)
            fmt = vals[0]
            if not fmt.type.is_string:
                raise AnalysisError("format() format must be a string")
            if fmt.value is None:
                return ir.Literal(None, T.VARCHAR)
            txt = str(fmt.value)
            # the reference uses Java's Formatter; the shared %s/%d/%x/%f
            # core maps 1:1 onto python %-formatting. %, separators and
            # argument indexes are not supported (AnalysisError below).
            try:
                out = txt % tuple(v.value for v in vals[1:])
            except (TypeError, ValueError) as ex:
                raise AnalysisError(f"format(): {ex}")
            return ir.Literal(out, T.VARCHAR)
        if name == "position":
            if len(e.args) != 2:
                raise AnalysisError("position() takes two arguments")
            sub = self.convert(e.args[0])
            hay = self.convert(e.args[1])
            if not isinstance(sub, ir.Literal):
                # the strpos binder's dictionary-table form needs a
                # constant needle; fail at ANALYSIS, not mid-execution
                raise AnalysisError(
                    "position(): the substring must be a constant"
                )
            return ir.Call("strpos", (hay, sub), T.BIGINT)
        if name == "concat_ws":
            if len(e.args) < 2:
                raise AnalysisError("concat_ws() needs separator + values")
            sep = self.convert(e.args[0])
            if not isinstance(sep, ir.Literal):
                raise AnalysisError("concat_ws() separator must be constant")
            if sep.value is None:
                return ir.Literal(None, T.VARCHAR)
            vals = [self.convert(a) for a in e.args[1:]]
            # NULL literals fold away here (the runtime Case below only
            # handles column nulls; the concat binder has no NULL-only
            # constant dictionary)
            vals = [
                v for v in vals
                if not (isinstance(v, ir.Literal) and v.value is None)
            ]
            if not vals:
                return ir.Literal("", T.VARCHAR)
            # NULL-skipping desugar: every NON-NULL value contributes
            # ``sep || value`` (NULL contributes ''), then ONE leading
            # separator is stripped — so NULLs vanish without doubling
            # separators while '' is kept (Trino's contract). Stays
            # inside the dictionary-concat machinery.
            sepl = ir.Literal(sep.value, T.VARCHAR)
            pieces = []
            for v in vals:
                sv = v if v.type.is_string else ir.Cast(v, T.VARCHAR)
                pieces.append(ir.Case(
                    (ir.is_null(v),), (ir.Literal("", T.VARCHAR),),
                    ir.Call("concat", (sepl, sv), T.VARCHAR), T.VARCHAR,
                ))
            glued = pieces[0]
            for p in pieces[1:]:
                glued = ir.Call("concat", (glued, p), T.VARCHAR)
            return ir.Call(
                "substr",
                (glued, ir.Literal(len(sep.value) + 1, T.BIGINT)),
                T.VARCHAR,
            )
        if name == "human_readable_seconds":
            _arity(1)
            (a,) = _need_const(e.args)
            if a.value is None:
                return ir.Literal(None, T.VARCHAR)
            secs = int(round(float(a.value)))
            units = [("week", 604800), ("day", 86400), ("hour", 3600),
                     ("minute", 60), ("second", 1)]
            neg, secs = secs < 0, abs(secs)
            parts = []
            for uname, u in units:
                q, secs = divmod(secs, u)
                if q:
                    parts.append(f"{q} {uname}{'s' if q != 1 else ''}")
            txt = ", ".join(parts) or "0 seconds"
            return ir.Literal(("-" if neg else "") + txt, T.VARCHAR)
        if name == "parse_duration":
            _arity(1)
            (a,) = _need_const(e.args)
            if a.value is None:
                return ir.Literal(None, T.INTERVAL_DAY)
            import re as _re

            m = _re.fullmatch(
                r"\s*([0-9.]+)\s*(ns|us|ms|s|m|h|d)\s*", str(a.value)
            )
            if not m:
                raise AnalysisError(f"invalid duration: {a.value!r}")
            mult = {"ns": 1e-3, "us": 1.0, "ms": 1e3, "s": 1e6,
                    "m": 6e7, "h": 3.6e9, "d": 8.64e10}[m.group(2)]
            return ir.Literal(
                int(float(m.group(1)) * mult), T.INTERVAL_DAY
            )
        if name == "parse_data_size":
            _arity(1)
            (a,) = _need_const(e.args)
            if a.value is None:
                return ir.Literal(None, T.decimal(38, 0))
            import re as _re

            m = _re.fullmatch(
                r"\s*([0-9.]+)\s*([kMGTPE]?B)\s*", str(a.value)
            )
            if not m:
                raise AnalysisError(f"invalid data size: {a.value!r}")
            exp = {"B": 0, "kB": 1, "MB": 2, "GB": 3, "TB": 4,
                   "PB": 5, "EB": 6}[m.group(2)]
            return ir.Literal(
                int(float(m.group(1)) * (1024 ** exp)),
                T.decimal(38, 0),
            )
        if name == "to_milliseconds":
            _arity(1)
            (a,) = _need_const(e.args)
            if a.type.kind != T.TypeKind.INTERVAL_DAY:
                raise AnalysisError(
                    "to_milliseconds() takes a day-to-second interval"
                )
            v = None if a.value is None else int(a.value) // 1000
            return ir.Literal(v, T.BIGINT)
        if name == "to_iso8601":
            _arity(1)
            (a,) = _need_const(e.args)
            if a.value is None:
                return ir.Literal(None, T.VARCHAR)
            if a.type.kind == T.TypeKind.DATE:
                d = _dt.date(1970, 1, 1) + _dt.timedelta(days=int(a.value))
                return ir.Literal(d.isoformat(), T.VARCHAR)
            if a.type.kind == T.TypeKind.TIMESTAMP:
                ts = _dt.datetime(1970, 1, 1) + _dt.timedelta(
                    microseconds=int(a.value)
                )
                return ir.Literal(ts.isoformat(), T.VARCHAR)
            raise AnalysisError("to_iso8601() takes a date or timestamp")
        if name == "to_base":
            _arity(2)
            a, r = _need_const(e.args)
            if a.value is None or r.value is None:
                return ir.Literal(None, T.VARCHAR)
            radix = int(r.value)
            if not 2 <= radix <= 36:
                raise AnalysisError("to_base() radix must be in [2, 36]")
            v, digits = abs(int(a.value)), "0123456789abcdefghijklmnopqrstuvwxyz"
            out = ""
            while True:
                v, rem = divmod(v, radix)
                out = digits[rem] + out
                if v == 0:
                    break
            return ir.Literal(
                ("-" if int(a.value) < 0 else "") + out, T.VARCHAR
            )
        if name in ("to_big_endian_32", "to_big_endian_64",
                    "to_ieee754_32", "to_ieee754_64"):
            import struct as _struct

            _arity(1)
            (a,) = _need_const(e.args)
            if a.value is None:
                return ir.Literal(None, T.VARCHAR)
            if name == "to_big_endian_32":
                b = int(a.value).to_bytes(4, "big", signed=True)
            elif name == "to_big_endian_64":
                b = int(a.value).to_bytes(8, "big", signed=True)
            elif name == "to_ieee754_32":
                b = _struct.pack(">f", float(a.value))
            else:
                b = _struct.pack(">d", float(a.value))
            # utf-8-replace decode: the engine's varbinary carrier (bytes
            # >= 0x80 do not round-trip — same documented limitation as
            # from_base64 of arbitrary bytes)
            return ir.Literal(b.decode("utf-8", "replace"), T.VARCHAR)
        if name == "format_number":
            _arity(1)
            (a,) = _need_const(e.args)
            if a.value is None:
                return ir.Literal(None, T.VARCHAR)
            v = float(a.value)
            for div, suf in ((1e12, "T"), (1e9, "B"), (1e6, "M"),
                             (1e3, "K")):
                if abs(v) >= div:
                    return ir.Literal(
                        f"{v / div:.2f}".rstrip("0").rstrip(".") + suf,
                        T.VARCHAR,
                    )
            txt = f"{v:.2f}".rstrip("0").rstrip(".")
            return ir.Literal(txt, T.VARCHAR)
        if name == "rgb":
            _arity(3)
            r, g, b = _need_const(e.args)
            if None in (r.value, g.value, b.value):
                return ir.Literal(None, T.BIGINT)
            for c in (r, g, b):
                if not 0 <= int(c.value) <= 255:
                    raise AnalysisError("rgb() components must be in [0,255]")
            return ir.Literal(
                (int(r.value) << 16) | (int(g.value) << 8) | int(b.value),
                T.BIGINT,
            )
        if name == "color":
            _arity(1)
            (a,) = _need_const(e.args)
            if a.value is None:
                return ir.Literal(None, T.BIGINT)
            s = str(a.value)
            named = {"black": 0x000000, "red": 0xFF0000, "green": 0x00FF00,
                     "yellow": 0xFFFF00, "blue": 0x0000FF,
                     "magenta": 0xFF00FF, "cyan": 0x00FFFF,
                     "white": 0xFFFFFF}
            if s.lower() in named:
                return ir.Literal(named[s.lower()], T.BIGINT)
            if s.startswith("#") and len(s) == 4:
                r, g, b = (int(c * 2, 16) for c in s[1:])
                return ir.Literal((r << 16) | (g << 8) | b, T.BIGINT)
            if s.startswith("#") and len(s) == 7:
                return ir.Literal(int(s[1:], 16), T.BIGINT)
            raise AnalysisError(f"invalid color: {s!r}")
        if name == "render":
            _arity(2)
            v, c = _need_const(e.args)
            if v.value is None or c.value is None:
                return ir.Literal(None, T.VARCHAR)
            rgb24 = int(c.value)
            r, g, b = (rgb24 >> 16) & 255, (rgb24 >> 8) & 255, rgb24 & 255
            return ir.Literal(
                f"\x1b[38;2;{r};{g};{b}m{v.value}\x1b[0m", T.VARCHAR
            )
        if name == "bar":
            _arity(2, 4)
            args = _need_const(e.args)
            if args[0].value is None or args[1].value is None:
                return ir.Literal(None, T.VARCHAR)
            x = float(args[0].value)
            width = int(args[1].value)
            lo = int(args[2].value) if len(args) > 2 else 0xFF0000
            hi = int(args[3].value) if len(args) > 3 else 0x00FF00
            x = min(max(x, 0.0), 1.0)
            n = int(round(x * width))
            out = []
            for i in range(n):
                t = i / max(width - 1, 1)
                r = int(((lo >> 16) & 255) * (1 - t) + ((hi >> 16) & 255) * t)
                g = int(((lo >> 8) & 255) * (1 - t) + ((hi >> 8) & 255) * t)
                b = int((lo & 255) * (1 - t) + (hi & 255) * t)
                out.append(f"\x1b[38;2;{r};{g};{b}m█")
            return ir.Literal(
                "".join(out) + ("\x1b[0m" if out else "") + " " * (width - n),
                T.VARCHAR,
            )
        return None

    def _convert_call(self, e: ast.FunctionCall) -> ir.Expr:
        name = e.name
        if name in AGG_FUNCS:
            raise AnalysisError(
                f"aggregate function {name}() in a non-aggregate context"
            )
        breadth = self._convert_breadth_call(name, e)
        if breadth is not None:
            return breadth
        if name in self._LAMBDA_FUNCS and len(e.args) == 2 and isinstance(
            e.args[1], ast.Lambda
        ):
            return self._convert_lambda_call(name, e)
        # constant-array functions fold at analysis time; column-typed
        # arguments vectorize over the nested layouts
        if name in ("cardinality", "element_at", "contains", "array_max",
                    "array_min", "array_join", "array_position",
                    "array_remove", "array_sort", "array_distinct",
                    "slice", "trim_array", "arrays_overlap",
                    "contains_sequence", "shuffle",
                    "array_intersect", "array_union", "array_except",
                    "flatten"):
            arr = (
                _const_array_values(e.args[0]) if e.args else None
            )
            if arr is None:
                if e.args:
                    ref = self.convert(e.args[0])
                    # cardinality vectorizes over the lengths array
                    # (ArrayColumn/MapColumn.data IS lengths)
                    if name == "cardinality" and (
                        ref.type.is_array or ref.type.is_map
                    ):
                        return ir.Call("array_length", (ref,), T.BIGINT)
                    if name == "cardinality" and ref.type.is_string:
                        # HyperLogLog estimate: sketches ride the
                        # varchar carrier (approx_set/merge), so a
                        # string cardinality() is unambiguously the HLL
                        # accessor (the reference types it HyperLogLog)
                        return ir.Call(
                            "hll_cardinality", (ref,), T.BIGINT
                        )
                    if name == "element_at" and ref.type.is_map:
                        key = self.convert(e.args[1])
                        return ir.Call(
                            "map_subscript", (ref, key), ref.type.element
                        )
                    if name == "element_at" and ref.type.is_array:
                        idx = self.convert(e.args[1])
                        return ir.Call(
                            "array_subscript", (ref, idx), ref.type.element
                        )
                    if name == "contains" and ref.type.is_array:
                        probe = self.convert(e.args[1])
                        return ir.Call(
                            "array_contains", (ref, probe), T.BOOLEAN
                        )
                    if name in ("array_min", "array_max") and ref.type.is_array:
                        return ir.Call(
                            f"{name}_col", (ref,), ref.type.element
                        )
                    if ref.type.is_array and name in (
                        "array_sort", "array_distinct", "array_remove",
                        "array_position", "slice", "trim_array",
                    ):
                        rest_ir = tuple(
                            self.convert(x) for x in e.args[1:]
                        )
                        out_t = (
                            T.BIGINT if name == "array_position"
                            else ref.type
                        )
                        return ir.Call(name, (ref,) + rest_ir, out_t)
                raise AnalysisError(
                    f"{name}() supports constant arrays"
                    + (" and array/map columns"
                       if name in ("cardinality", "element_at") else "")
                    + " only"
                )
            return self._fold_array_call(name, arr, e.args[1:])
        if name in self._LAMBDA_FUNCS:
            raise AnalysisError(
                f"{name}() takes a lambda as its second argument"
            )
        if name in ("map_keys", "map_values"):
            ref = self.convert(e.args[0]) if e.args else None
            if ref is None or not ref.type.is_map:
                raise AnalysisError(f"{name}() requires a map argument")
            out_t = T.array_of(
                ref.type.key if name == "map_keys" else ref.type.element
            )
            return ir.Call(name, (ref,), out_t)
        if name == "row":
            args = tuple(self.convert(a) for a in e.args)
            return ir.Call(
                "row_pack", args, T.row_of(*[a.type for a in args])
            )
        if name == "sequence":
            raise AnalysisError(
                "sequence() is usable inside UNNEST or array functions"
            )
        args = tuple(self.convert(a) for a in e.args)
        if name in ("substr", "substring"):
            return ir.Call("substr", args, T.VARCHAR)
        return self._convert_plain_call(name, e, args)

    def _convert_lambda_call(self, name: str, e: ast.FunctionCall) -> ir.Expr:
        coll = self.convert(e.args[0])
        lam: ast.Lambda = e.args[1]
        if name in ("transform", "filter", "any_match", "all_match",
                    "none_match"):
            if not coll.type.is_array:
                raise AnalysisError(f"{name}() requires an array argument")
            if len(lam.params) != 1:
                raise AnalysisError(f"{name}() lambda takes one parameter")
            param_types = [coll.type.element]
        else:
            if not coll.type.is_map:
                raise AnalysisError(f"{name}() requires a map argument")
            if len(lam.params) != 2:
                raise AnalysisError(f"{name}() lambda takes (key, value)")
            param_types = [coll.type.key, coll.type.element]
        prev = getattr(self, "_lambda_scope", None)
        self._lambda_scope = {
            p: ir.LambdaVar(i, t)
            for i, (p, t) in enumerate(zip(lam.params, param_types))
        }
        try:
            body = self.convert(lam.body)
        finally:
            self._lambda_scope = prev
        if _refers_outside_lambda(body):
            raise AnalysisError(
                f"{name}() lambda may only reference its parameters "
                "(outer-column captures are not supported yet)"
            )
        lam_ir = ir.LambdaExpr(body, len(lam.params), body.type)
        if name == "transform":
            out_t = T.array_of(body.type)
        elif name == "filter":
            out_t = coll.type
        elif name in ("any_match", "all_match", "none_match"):
            if body.type.kind != T.TypeKind.BOOLEAN:
                raise AnalysisError(f"{name}() lambda must return boolean")
            out_t = T.BOOLEAN
        elif name == "map_filter":
            if body.type.kind != T.TypeKind.BOOLEAN:
                raise AnalysisError(f"{name}() lambda must return boolean")
            out_t = coll.type
        elif name == "transform_values":
            out_t = T.map_of(coll.type.key, body.type)
        else:  # transform_keys
            out_t = T.map_of(body.type, coll.type.element)
        return ir.Call(name, (coll, lam_ir), out_t)

    def _convert_plain_call(self, name, e, args) -> ir.Expr:
        if name in ("upper", "lower"):
            return ir.Call(name, args, T.VARCHAR)
        if name == "length":
            return ir.Call(name, args, T.BIGINT)
        if name == "abs":
            return ir.Call(name, args, args[0].type)
        if name == "round":
            return ir.Call(name, args, args[0].type)
        if name in ("sqrt", "ln", "exp"):
            return ir.Call(name, args, T.DOUBLE)
        if name in ("floor", "ceil", "ceiling"):
            nm = "ceil" if name == "ceiling" else name
            out = T.DOUBLE if args[0].type.is_floating else T.BIGINT
            return ir.Call(nm, args, out)
        if name == "coalesce":
            out = _unify_types([a.type for a in args])
            return ir.Call(name, args, out)
        if name == "concat":
            return ir.Call("concat", args, T.VARCHAR)
        if name in ("trim", "ltrim", "rtrim", "reverse"):
            return ir.Call(name, args, T.VARCHAR)
        if name == "replace":
            return ir.Call(name, args, T.VARCHAR)
        if name == "starts_with":
            return ir.Call(name, args, T.BOOLEAN)
        if name == "nullif":
            if len(args) != 2:
                raise AnalysisError("nullif() takes two arguments")
            return ir.Call(name, args, args[0].type)
        if name in ("greatest", "least"):
            out = _unify_types([a.type for a in args])
            cast_args = tuple(
                a if a.type == out else ir.Cast(a, out) for a in args
            )
            return ir.Call(name, cast_args, out)
        if name in ("power", "pow"):
            return ir.Call("power", args, T.DOUBLE)
        if name in ("log2", "log10"):
            return ir.Call(name, args, T.DOUBLE)
        if name == "sign":
            out = T.DOUBLE if args[0].type.is_floating else T.BIGINT
            return ir.Call(name, args, out)
        if name == "mod":
            out_t = _arith_type("mod", args[0].type, args[1].type)
            return ir.Call("mod", args, out_t)
        if (
            name in _TSTZ_WALL_FNS
            and args
            and args[0].type.kind == T.TypeKind.TIMESTAMP_TZ
        ):
            # civil-field/formatting functions read the LOCAL wall clock
            # in the value's own zone (DateTimes.java) — rewrite the
            # tstz argument to its wall-clock timestamp
            args = [
                ir.Call("tstz_to_ts", (args[0],), T.TIMESTAMP), *args[1:]
            ]
        if name in ("year", "month", "day"):
            return ir.Call(f"extract_{name}", args, T.BIGINT)
        if name == "if":
            if len(args) not in (2, 3):
                raise AnalysisError("if() takes 2 or 3 arguments")
            default = args[2] if len(args) == 3 else None
            out = _unify_types(
                [args[1].type] + ([default.type] if default is not None else [])
            )
            return ir.Case((args[0],), (args[1],), default, out)
        if name in ("sin", "cos", "tan", "asin", "acos", "atan", "sinh",
                    "cosh", "tanh", "cbrt", "degrees", "radians"):
            return ir.Call(name, args, T.DOUBLE)
        if name in ("atan2", "log"):
            if len(args) != 2:
                raise AnalysisError(f"{name}() takes two arguments")
            return ir.Call(name, args, T.DOUBLE)
        if name == "pi":
            return ir.Literal(math.pi, T.DOUBLE)
        if name == "e":
            return ir.Literal(math.e, T.DOUBLE)
        if name == "nan":
            return ir.Literal(float("nan"), T.DOUBLE)
        if name == "infinity":
            return ir.Literal(float("inf"), T.DOUBLE)
        if name in ("is_nan", "is_infinite", "is_finite"):
            return ir.Call(name, args, T.BOOLEAN)
        if name == "truncate":
            out = args[0].type if args[0].type.is_decimal else T.DOUBLE
            return ir.Call(name, args, out)
        if name in ("bitwise_and", "bitwise_or", "bitwise_xor",
                    "bitwise_not", "bitwise_left_shift",
                    "bitwise_right_shift"):
            return ir.Call(name, args, T.BIGINT)
        if name in ("strpos", "codepoint"):
            return ir.Call(name, args, T.BIGINT)
        if name in ("ends_with", "regexp_like"):
            return ir.Call(name, args, T.BOOLEAN)
        if name in ("split_part", "lpad", "rpad", "translate",
                    "regexp_extract", "regexp_replace"):
            return ir.Call(name, args, T.VARCHAR)
        if name == "regexp_count":
            return ir.Call(name, args, T.BIGINT)
        if name == "chr":
            if not isinstance(args[0], ir.Literal):
                raise AnalysisError("chr() argument must be a constant")
            return ir.Literal(chr(int(args[0].value)), T.VARCHAR)
        TSTZ_K = T.TypeKind.TIMESTAMP_TZ
        if name == "to_unixtime" and args and args[0].type.kind == TSTZ_K:
            # unix time is the INSTANT, not the wall clock
            args = [
                ir.Call("tstz_to_instant_ts", (args[0],), T.TIMESTAMP),
                *args[1:],
            ]
        if name in ("quarter", "week", "day_of_week", "dow", "day_of_year",
                    "doy", "day_of_month"):
            canon = {"dow": "day_of_week", "doy": "day_of_year",
                     "day_of_month": "extract_day"}.get(name, name)
            return ir.Call(canon, args, T.BIGINT)
        if name == "date_trunc":
            if len(args) != 2:
                raise AnalysisError("date_trunc() takes two arguments")
            if args[1].type.kind == TSTZ_K:
                # truncate on the wall clock in the value's zone, then
                # restore the instant/zone packing (DateTimes.java
                # truncation semantics)
                wall = ir.Call("tstz_to_ts", (args[1],), T.TIMESTAMP)
                trunc = ir.Call("date_trunc", (args[0], wall), T.TIMESTAMP)
                return ir.Call(
                    "tstz_rewall", (trunc, args[1]), T.TIMESTAMP_TZ
                )
            return ir.Call(name, args, args[1].type)
        if name == "date_add":
            if len(args) != 3:
                raise AnalysisError("date_add() takes three arguments")
            if args[2].type.kind == TSTZ_K:
                unit = (
                    str(args[0].value).lower()
                    if isinstance(args[0], ir.Literal) else None
                )
                sub_day = {"millisecond": 1, "second": 1000,
                           "minute": 60_000, "hour": 3_600_000}
                if unit in sub_day:
                    # exact-duration shift on the instant
                    ms = ir.Call(
                        "mul",
                        (args[1], ir.Literal(sub_day[unit], T.BIGINT)),
                        T.BIGINT,
                    )
                    return ir.Call(
                        "tstz_shift", (args[2], ms), T.TIMESTAMP_TZ
                    )
                # calendar units move the wall clock in the value's zone
                wall = ir.Call("tstz_to_ts", (args[2],), T.TIMESTAMP)
                moved = ir.Call(
                    "date_add", (args[0], args[1], wall), T.TIMESTAMP
                )
                return ir.Call(
                    "tstz_rewall", (moved, args[2]), T.TIMESTAMP_TZ
                )
            return ir.Call(name, args, args[2].type)
        if name == "date_diff":
            if len(args) != 3:
                raise AnalysisError("date_diff() takes three arguments")
            if any(a.type.kind == TSTZ_K for a in args[1:]):
                unit = (
                    str(args[0].value).lower()
                    if isinstance(args[0], ir.Literal) else None
                )
                sub_day = ("millisecond", "second", "minute", "hour")
                conv = (
                    "tstz_to_instant_ts" if unit in sub_day else "tstz_to_ts"
                )
                new_args = [args[0]]
                for a in args[1:]:
                    if a.type.kind == TSTZ_K:
                        a = ir.Call(conv, (a,), T.TIMESTAMP)
                    new_args.append(a)
                return ir.Call(name, tuple(new_args), T.BIGINT)
            return ir.Call(name, args, T.BIGINT)
        if name == "last_day_of_month":
            return ir.Call(name, args, T.DATE)
        if name == "typeof":
            if len(args) != 1:
                raise AnalysisError("typeof() takes one argument")
            return ir.Literal(str(args[0].type), T.VARCHAR)
        # registry-resolved scalars (expr/registry.py): every function
        # not special-cased above types through the declarative catalog
        # (FunctionResolver analogue)
        from trino_tpu.expr.registry import REGISTRY

        try:
            hit = REGISTRY.resolve(name, [a.type for a in args])
        except ValueError as ex:
            raise AnalysisError(str(ex))
        if hit is not None:
            canonical, out_t = hit
            meta = REGISTRY.get(name)
            for pos in meta.const_args:
                if pos < len(args) and not isinstance(args[pos], ir.Literal):
                    raise AnalysisError(
                        f"{meta.name}(): argument {pos + 1} must be a"
                        " constant"
                    )
            return ir.Call(canonical, args, out_t)
        raise AnalysisError(f"unknown function {name}()")

    def _convert_subscript(self, e) -> ir.Expr:
        """a[i] / m[k] (Trino's SubscriptExpression). Missing map keys
        and out-of-range array positions yield NULL (element_at
        semantics; the reference raises for bare [] on missing keys —
        documented divergence, NULL degrades instead of failing)."""
        if isinstance(e.operand, ast.ArrayLiteral):
            arr = _const_array_values(e.operand)
            if arr is not None:
                return self._fold_array_call("element_at", arr, (e.index,))
        base = self.convert(e.operand)
        idx = self.convert(e.index)
        if base.type.is_map:
            return ir.Call("map_subscript", (base, idx), base.type.element)
        if base.type.is_array:
            return ir.Call("array_subscript", (base, idx), base.type.element)
        raise AnalysisError(
            f"subscript requires an array or map operand, got {base.type}"
        )

    def _fold_array_call(
        self, name: str, arr: List[ir.Literal], rest: tuple
    ) -> ir.Expr:
        elem_t = _array_element_type(arr)  # raises on mixed types
        if name == "cardinality":
            return ir.Literal(len(arr), T.BIGINT)
        if name == "element_at":
            idx = _const_fold(self.convert(rest[0])) if rest else None
            if idx is None or idx.value is None:
                raise AnalysisError("element_at() index must be constant")
            i = int(idx.value)
            # 1-based; negative counts from the end; OOB -> NULL
            pos = i - 1 if i > 0 else len(arr) + i
            if i == 0:
                raise AnalysisError("element_at() index cannot be 0")
            if 0 <= pos < len(arr):
                return arr[pos]
            return ir.Literal(None, elem_t)
        if name == "contains":
            probe = _const_fold(self.convert(rest[0])) if rest else None
            if probe is None:
                raise AnalysisError("contains() value must be constant")
            if probe.value is None:
                return ir.Literal(None, T.BOOLEAN)  # NULL probe -> NULL
            if (
                probe.type.kind != T.TypeKind.UNKNOWN
                and arr
                and T.common_super_type(elem_t, probe.type) is None
            ):
                raise AnalysisError(
                    f"contains(): cannot compare {elem_t} with {probe.type}"
                )
            # avoid python bool==int conflation: compare type kinds too
            def same(a, b):
                return a == b and isinstance(a, bool) == isinstance(b, bool)

            if any(
                l.value is not None and same(l.value, probe.value)
                for l in arr
            ):
                return ir.Literal(True, T.BOOLEAN)
            # NULL element makes a non-match indeterminate (SQL IN)
            if any(l.value is None for l in arr):
                return ir.Literal(None, T.BOOLEAN)
            return ir.Literal(False, T.BOOLEAN)
        if name in ("array_max", "array_min"):
            vals = [l.value for l in arr if l.value is not None]
            if not vals or len(vals) != len(arr):  # Trino: NULL if any NULL
                return ir.Literal(None, elem_t)
            return ir.Literal(
                max(vals) if name == "array_max" else min(vals), elem_t
            )
        if name == "array_join":
            sep = _const_fold(self.convert(rest[0])) if rest else None
            if sep is None or sep.value is None:
                raise AnalysisError("array_join() delimiter must be constant")
            null_repl = None
            if len(rest) > 1:
                nr = _const_fold(self.convert(rest[1]))
                if nr is None:
                    raise AnalysisError(
                        "array_join() null replacement must be constant"
                    )
                null_repl = nr.value  # NULL replacement -> skip nulls
            parts = []
            for l in arr:
                if l.value is None:
                    if null_repl is not None:
                        parts.append(str(null_repl))
                else:
                    v = l.value
                    parts.append(
                        ("true" if v else "false")
                        if isinstance(v, bool) else str(v)
                    )
            return ir.Literal(str(sep.value).join(parts), T.VARCHAR)
        # r4 breadth: constant-array forms fold at analysis; COLUMN
        # arrays take the vectorized binder paths (expr/compile
        # _bind_array_fn) where layouts are canonical
        vals = [l.value for l in arr]

        def lit_arr(pyvals, t=None):
            return ir.Literal(tuple(pyvals), T.array_of(t or elem_t))

        def other_array(idx=0):
            o = _const_array_values(rest[idx]) if len(rest) > idx else None
            if o is None:
                raise AnalysisError(f"{name}() requires constant arrays")
            return [
                _const_fold(self.convert(x)).value for x in rest[idx].elements
            ]

        if name == "array_position":
            probe = _const_fold(self.convert(rest[0])) if rest else None
            if probe is None:
                raise AnalysisError("array_position() value must be constant")
            for i, v in enumerate(vals):
                if v is not None and v == probe.value:
                    return ir.Literal(i + 1, T.BIGINT)
            return ir.Literal(0, T.BIGINT)
        if name == "array_remove":
            probe = _const_fold(self.convert(rest[0])) if rest else None
            if probe is None:
                raise AnalysisError("array_remove() value must be constant")
            return lit_arr([v for v in vals if v is None or v != probe.value])
        if name == "array_sort":
            nn = sorted(v for v in vals if v is not None)
            return lit_arr(nn + [None] * (len(vals) - len(nn)))
        if name == "array_distinct":
            seen, out = set(), []
            has_null = False
            for v in vals:
                if v is None:
                    has_null = True
                elif v not in seen:
                    seen.add(v)
                    out.append(v)
            return lit_arr(out + ([None] if has_null else []))
        if name in ("slice", "trim_array"):
            a1 = _const_fold(self.convert(rest[0]))
            if name == "trim_array":
                n = int(a1.value)
                return lit_arr(vals[: max(len(vals) - n, 0)])
            a2 = _const_fold(self.convert(rest[1]))
            start, ln = int(a1.value), int(a2.value)
            pos = start - 1 if start > 0 else len(vals) + start
            return lit_arr(vals[max(pos, 0): max(pos, 0) + max(ln, 0)])
        if name in ("arrays_overlap", "array_intersect", "array_union",
                    "array_except"):
            other = other_array()
            sa = [v for v in vals if v is not None]
            sb = [v for v in other if v is not None]
            if name == "arrays_overlap":
                if set(sa) & set(sb):
                    return ir.Literal(True, T.BOOLEAN)
                if None in vals or None in other:
                    return ir.Literal(None, T.BOOLEAN)
                return ir.Literal(False, T.BOOLEAN)
            if name == "array_intersect":
                return lit_arr(sorted(set(sa) & set(sb)))
            if name == "array_union":
                u = sorted(set(sa) | set(sb))
                if None in vals or None in other:
                    u = u + [None]
                return lit_arr(u)
            return lit_arr(sorted(set(sa) - set(sb)))
        if name == "flatten":
            out = []
            for x in arr:  # elements are themselves array literals
                if x.value is None:
                    continue
                out.extend(x.value)
            return lit_arr(out, elem_t.element if elem_t.is_array else elem_t)
        if name == "contains_sequence":
            seq = other_array()
            n, m = len(vals), len(seq)
            hit = any(
                list(vals[i:i + m]) == list(seq)
                for i in range(n - m + 1)
            ) or m == 0
            return ir.Literal(hit, T.BOOLEAN)
        if name == "shuffle":
            import random as _random

            out = list(vals)
            _random.shuffle(out)  # nondeterministic, like the reference
            return lit_arr(out)
        raise AnalysisError(f"unknown array function {name}")


# ---------------------------------------------------------------------------
# Helpers over AST predicates
# ---------------------------------------------------------------------------


def split_conjuncts(e: Optional[ast.Expression]) -> List[ast.Expression]:
    if e is None:
        return []
    if isinstance(e, ast.BinaryOp) and e.op == "and":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def conjoin(parts: Sequence[ast.Expression]) -> Optional[ast.Expression]:
    out = None
    for p in parts:
        out = p if out is None else ast.BinaryOp("and", out, p)
    return out


def _idents(e: ast.Expression) -> List[ast.Identifier]:
    """All identifiers in an expression, NOT descending into subqueries."""
    out: List[ast.Identifier] = []

    def walk(x):
        if isinstance(x, ast.Identifier):
            out.append(x)
            return
        if isinstance(x, (ast.Exists, ast.InSubquery, ast.ScalarSubquery)):
            return  # inner scope owns those
        if dataclasses.is_dataclass(x):
            for f in dataclasses.fields(x):
                walk(getattr(x, f.name))
        elif isinstance(x, tuple):
            for i in x:
                walk(i)

    walk(e)
    return out


def _has_subquery(e: ast.Expression) -> bool:
    found = False

    def walk(x):
        nonlocal found
        if found:
            return
        if isinstance(x, (ast.Exists, ast.InSubquery, ast.ScalarSubquery)):
            found = True
            return
        if dataclasses.is_dataclass(x):
            for f in dataclasses.fields(x):
                walk(getattr(x, f.name))
        elif isinstance(x, tuple):
            for i in x:
                walk(i)

    walk(e)
    return found


def _scalar_subqueries(e: ast.Expression) -> List[ast.ScalarSubquery]:
    out: List[ast.ScalarSubquery] = []

    def walk(x):
        if isinstance(x, ast.ScalarSubquery):
            out.append(x)
            return
        if isinstance(x, (ast.Exists, ast.InSubquery)):
            return
        if dataclasses.is_dataclass(x):
            for f in dataclasses.fields(x):
                walk(getattr(x, f.name))
        elif isinstance(x, tuple):
            for i in x:
                walk(i)

    walk(e)
    return out


WINDOW_ONLY_FUNCS = {
    "row_number", "rank", "dense_rank", "percent_rank", "cume_dist",
    "ntile", "lead", "lag", "first_value", "last_value", "nth_value",
}


def _find_window_calls(e: ast.Expression) -> List[ast.WindowCall]:
    out: List[ast.WindowCall] = []

    def walk(x):
        if isinstance(x, ast.WindowCall):
            out.append(x)
            return
        if isinstance(x, (ast.Exists, ast.InSubquery, ast.ScalarSubquery)):
            return
        if dataclasses.is_dataclass(x):
            for f in dataclasses.fields(x):
                walk(getattr(x, f.name))
        elif isinstance(x, tuple):
            for i in x:
                walk(i)

    walk(e)
    return out


def resolve_type(t: ast.TypeName) -> T.DataType:
    """TypeName AST -> DataType (shared by CAST analysis and DDL)."""
    mapping = {
        "boolean": T.BOOLEAN, "tinyint": T.TINYINT, "smallint": T.SMALLINT,
        "integer": T.INTEGER, "bigint": T.BIGINT, "real": T.REAL,
        "double": T.DOUBLE, "date": T.DATE, "timestamp": T.TIMESTAMP,
        "timestamp with time zone": T.TIMESTAMP_TZ,
    }
    if t.name in mapping:
        return mapping[t.name]
    if t.name == "decimal":
        p = t.params[0] if t.params else 18
        s = t.params[1] if len(t.params) > 1 else 0
        return T.decimal(min(p, T.MAX_DECIMAL_PRECISION), s)
    if t.name in ("varchar", "char"):
        return T.VARCHAR
    if t.name == "array":
        return T.array_of(resolve_type(t.args[0][1]))
    if t.name == "map":
        return T.map_of(resolve_type(t.args[0][1]), resolve_type(t.args[1][1]))
    if t.name == "row":
        return T.row_of(*[(n, resolve_type(st)) for n, st in t.args])
    raise AnalysisError(f"unsupported type {t.name}")


def _array_element_type(arr: List[ir.Literal]) -> T.DataType:
    """Unified element type; mixed incompatible elements fail loudly at
    analysis time (ARRAY[1, 'a'] must not crash at execution)."""
    t: Optional[T.DataType] = None
    for lit in arr:
        if lit.type.kind == T.TypeKind.UNKNOWN:
            continue
        if t is None:
            t = lit.type
            continue
        u = T.common_super_type(t, lit.type)
        if u is None:
            raise AnalysisError(
                f"array elements have incompatible types {t} and {lit.type}"
            )
        t = u
    return t or T.BIGINT


def _const_array_values(e: ast.Expression) -> Optional[List[ir.Literal]]:
    """Fold a constant array expression (ARRAY[...] of foldable cells,
    or sequence(lo, hi[, step]) with literal bounds) to its elements."""
    conv = ExprConverter(Scope([]))
    if isinstance(e, ast.ArrayLiteral):
        out = []
        for cell in e.elements:
            lit = _const_fold(conv.convert(cell))
            if lit is None:
                return None
            out.append(lit)
        return out
    if isinstance(e, ast.FunctionCall) and e.name == "sequence":
        args = [_const_fold(conv.convert(a)) for a in e.args]
        if any(a is None or a.value is None for a in args):
            return None
        if len(args) == 2:
            lo, hi, step = int(args[0].value), int(args[1].value), 1
        elif len(args) == 3:
            lo, hi, step = (int(a.value) for a in args)
        else:
            raise AnalysisError("sequence() takes 2 or 3 arguments")
        if step == 0:
            raise AnalysisError("sequence() step must not be zero")
        if (hi - lo) * step < 0:
            raise AnalysisError(
                "sequence() step sign contradicts the start/stop direction"
            )
        if abs((hi - lo) // step) > 1_000_000:
            raise AnalysisError("sequence() result too large")
        stop = hi + (1 if step > 0 else -1)
        return [
            ir.Literal(v, T.BIGINT) for v in range(lo, stop, step)
        ]
    return None


def _const_fold(x: ir.Expr) -> Optional[ir.Literal]:
    """Literal, negate(Literal) or cast(Literal) -> folded Literal."""
    if isinstance(x, ir.Literal):
        return x
    if isinstance(x, ir.Call) and x.name == "negate":
        inner = _const_fold(x.args[0])
        if inner is not None and inner.value is not None:
            return ir.Literal(-inner.value, x.type)
    if isinstance(x, ir.Cast):
        inner = _const_fold(x.arg)
        if inner is not None:
            return ir.Literal(inner.value, x.type)
    return None


def _refers_outside_lambda(body: ir.Expr) -> bool:
    """True when a lambda body references anything but its parameters
    and constants (outer-column captures — unsupported)."""
    if isinstance(body, ir.InputRef):
        return True
    return any(_refers_outside_lambda(c) for c in body.children())


# scalar accessors that FUSE with the sketch aggregate they wrap:
# cardinality(approx_set(x)) etc. evaluate inside the aggregation's
# collect finalizer, because the digest's runtime dictionary is not
# plan-bindable (expr/compile dictionary-table discipline). Standalone
# accessors over TABLE columns bind normally.
_SKETCH_ACCESSORS = {"cardinality", "value_at_quantile",
                     "quantile_at_value", "values_at_quantiles"}
_SKETCH_AGGS = {"approx_set", "merge", "tdigest_agg", "qdigest_agg"}


def _find_agg_calls(e: ast.Expression) -> List[ast.FunctionCall]:
    out: List[ast.FunctionCall] = []

    def walk(x):
        if (
            isinstance(x, ast.FunctionCall)
            and x.name in _SKETCH_ACCESSORS
            and x.args
            and isinstance(x.args[0], ast.FunctionCall)
            and x.args[0].name in _SKETCH_AGGS
        ):
            out.append(x)  # fused accessor-over-sketch unit
            return
        if isinstance(x, ast.FunctionCall) and x.name in AGG_FUNCS:
            out.append(x)
            return  # no nested aggregates
        if isinstance(x, (ast.Exists, ast.InSubquery, ast.ScalarSubquery)):
            return
        if dataclasses.is_dataclass(x):
            for f in dataclasses.fields(x):
                walk(getattr(x, f.name))
        elif isinstance(x, tuple):
            for i in x:
                walk(i)

    walk(e)
    return out


def _common_or_conjuncts(e: ast.Expression) -> List[ast.Expression]:
    """Factor conjuncts common to every branch of an OR (Q19's
    `p_partkey = l_partkey` pattern) — ExtractCommonPredicatesExpressionRewriter
    analogue. The OR itself stays; the extracted conjuncts are implied."""
    branches: List[ast.Expression] = []

    def flatten_or(x):
        if isinstance(x, ast.BinaryOp) and x.op == "or":
            flatten_or(x.left)
            flatten_or(x.right)
        else:
            branches.append(x)

    flatten_or(e)
    if len(branches) < 2:
        return []
    sets = [split_conjuncts(b) for b in branches]
    common = [c for c in sets[0] if all(c in s for s in sets[1:])]
    return common


# ---------------------------------------------------------------------------
# Plan builder
# ---------------------------------------------------------------------------


class Builder:
    """Mutable (node, scope, replacements) triple threaded through
    planning; replacements map AST expressions to output channels."""

    def __init__(self, node: P.PlanNode, scope: Scope):
        self.node = node
        self.scope = scope
        self.replacements: Dict[ast.Expression, Tuple[int, T.DataType]] = {}

    def converter(self) -> ExprConverter:
        return ExprConverter(self.scope, self.replacements)

    def filter(self, predicate: ir.Expr) -> None:
        self.node = P.FilterNode(self.node, predicate, self.node.fields)


@dataclasses.dataclass
class _DeferredUnnest:
    """Marker for UNNEST over column references; resolved against the
    sibling FROM items after they all plan."""

    rel: "ast.UnnestRelation"


@dataclasses.dataclass
class RelationItem:
    """One FROM item during join planning."""

    node: P.PlanNode
    scope: Scope
    rows: float  # stats estimate


class Analyzer:
    def __init__(self, catalogs: CatalogManager, default_catalog: str, default_schema: str):
        self.catalogs = catalogs
        self.catalog = default_catalog
        self.schema = default_schema

    # ---- statements ----
    def plan(self, stmt: ast.Node) -> P.OutputNode:
        if isinstance(stmt, ast.Query):
            node, scope, names = self.plan_query(stmt, {})
            out = P.OutputNode(node, tuple(names), node.fields)
            _validate_array_usage(out)
            return out
        raise AnalysisError(f"cannot plan {type(stmt).__name__}")

    # ---- queries ----
    def plan_query(
        self, q: ast.Query, ctes: Dict[str, ast.WithQuery]
    ) -> Tuple[P.PlanNode, Scope, List[str]]:
        ctes = dict(ctes)
        for w in q.with_:
            ctes[w.name] = w
        if isinstance(q.body, ast.QuerySpec):
            return self.plan_query_spec(
                q.body, q.order_by, q.limit, q.offset, ctes
            )
        if isinstance(q.body, ast.SetOperation):
            return self._plan_set_op(q, ctes)
        if isinstance(q.body, ast.ValuesBody):
            if q.order_by or q.limit is not None or q.offset:
                raise AnalysisError("ORDER BY/LIMIT over VALUES not supported")
            return self._plan_values_body(q.body)
        raise AnalysisError("unsupported query body")

    def _plan_values_body(self, body: ast.ValuesBody):
        """VALUES rows -> ValuesNode: cells must be constant-foldable
        (Values analogue of parser/sql/tree/Values)."""
        conv = ExprConverter(Scope([]))
        rows = []
        col_types: List[Optional[T.DataType]] = []
        for r in body.rows:
            vals = []
            for i, cell in enumerate(r):
                lit = _const_fold(conv.convert(cell))
                if lit is None:
                    raise AnalysisError("VALUES cells must be constants")
                vals.append(lit.value)
                t = lit.type
                if i >= len(col_types):
                    col_types.append(t)
                else:
                    prev = col_types[i]
                    if prev is None or prev.kind == T.TypeKind.UNKNOWN:
                        col_types[i] = t
                    elif t.kind != T.TypeKind.UNKNOWN and t != prev:
                        u = T.common_super_type(prev, t)
                        if u is None:
                            raise AnalysisError(
                                f"VALUES column {i}: incompatible types {prev} and {t}"
                            )
                        col_types[i] = u
            if len(r) != len(body.rows[0]):
                raise AnalysisError("VALUES rows differ in width")
            rows.append(tuple(vals))
        types = [
            t if t is not None and t.kind != T.TypeKind.UNKNOWN else T.BIGINT
            for t in col_types
        ]
        names = [f"_col{i}" for i in range(len(types))]
        fields = tuple(P.Field(n, t) for n, t in zip(names, types))
        node = P.ValuesNode(fields, tuple(rows))
        scope = Scope([ScopeField(None, n, t) for n, t in zip(names, types)])
        return node, scope, names

    def _plan_set_op(self, q: ast.Query, ctes) -> Tuple[P.PlanNode, Scope, List[str]]:
        def plan_body(body) -> Tuple[P.PlanNode, Scope, List[str]]:
            if isinstance(body, ast.QuerySpec):
                return self.plan_query_spec(body, (), None, 0, ctes)
            if isinstance(body, ast.SetOperation):
                return plan_set(body)
            if isinstance(body, ast.ValuesBody):
                return self._plan_values_body(body)
            raise AnalysisError("unsupported set operation term")

        def plan_set(s: ast.SetOperation) -> Tuple[P.PlanNode, Scope, List[str]]:
            ln, lscope, lnames = plan_body(s.left)
            rn, rscope, _ = plan_body(s.right)
            if len(lscope) != len(rscope):
                raise AnalysisError("set operation inputs differ in width")
            for lf, rf in zip(ln.fields, rn.fields):
                if lf.type != rf.type:
                    raise AnalysisError(
                        f"set operation column types differ: {lf.type} vs {rf.type}"
                    )
            fields = ln.fields
            if s.op == "union":
                node: P.PlanNode = P.UnionAllNode((ln, rn), fields)
                if not s.all:
                    node = P.AggregateNode(
                        node, tuple(range(len(fields))), (), fields
                    )
            else:
                # INTERSECT/EXCEPT via dedup + semi/anti join on all
                # columns (the SetOperationNodeTranslator strategy).
                # Deviation: NULL rows follow join semantics (never
                # match), not the standard's NULLs-equal grouping.
                if s.all:
                    raise AnalysisError(f"{s.op} ALL not supported")
                w = len(fields)
                dedup = P.AggregateNode(ln, tuple(range(w)), (), fields)
                kind = "semi" if s.op == "intersect" else "anti"
                node = P.JoinNode(
                    kind, dedup, rn, tuple(range(w)), tuple(range(w)),
                    None, fields,
                )
            return node, Scope([ScopeField(None, f.name, f.type) for f in fields]), lnames

        node, scope, names = plan_set(q.body)
        # ORDER BY / LIMIT / OFFSET over the set operation's output
        sort_keys: List[SortKey] = []
        for s in q.order_by:
            ch = None
            if isinstance(s.expr, ast.NumberLiteral) and s.expr.text.isdigit():
                ch = int(s.expr.text) - 1
            elif isinstance(s.expr, ast.Identifier) and len(s.expr.parts) == 1:
                name = s.expr.parts[0]
                if name in names:
                    ch = names.index(name)
            if ch is None or not (0 <= ch < len(names)):
                raise AnalysisError(
                    "ORDER BY over set operations must reference output columns"
                )
            nf = s.nulls_first if s.nulls_first is not None else s.descending
            sort_keys.append(SortKey(ch, s.descending, nf))
        if sort_keys:
            if q.limit is not None and not q.offset:
                node = P.TopNNode(node, tuple(sort_keys), q.limit, node.fields)
            else:
                node = P.SortNode(node, tuple(sort_keys), node.fields)
                if q.limit is not None or q.offset:
                    node = P.LimitNode(node, q.limit, q.offset, node.fields)
        elif q.limit is not None or q.offset:
            node = P.LimitNode(node, q.limit, q.offset, node.fields)
        return node, scope, names

    # ---- the heart: one SELECT block ----
    def plan_query_spec(
        self,
        spec: ast.QuerySpec,
        order_by: Tuple[ast.SortItem, ...],
        limit: Optional[int],
        offset: int,
        ctes: Dict[str, ast.WithQuery],
    ) -> Tuple[P.PlanNode, Scope, List[str]]:
        builder, leftovers = self._plan_from_where(spec, ctes)

        # remaining predicates (subqueries, cross-item non-equi, ...)
        for conj in leftovers:
            self._plan_predicate(builder, conj, ctes)

        # -- aggregation analysis --
        select_items = self._expand_stars(spec, builder.scope)
        select_exprs = [it.expr for it in select_items]
        group_asts = self._resolve_group_ordinals(spec.group_by, select_exprs)
        agg_calls: List[ast.FunctionCall] = []
        for e in select_exprs + ([spec.having] if spec.having else []) + [
            s.expr for s in order_by
        ]:
            for c in _find_agg_calls(e):
                if c not in agg_calls:
                    agg_calls.append(c)
        if spec.group_by_sets is not None:
            self._plan_grouping_sets(
                builder, group_asts, spec.group_by_sets, agg_calls, ctes
            )
            if spec.having is not None:
                self._plan_predicate(builder, spec.having, ctes)
        elif group_asts or agg_calls:
            self._plan_aggregation(builder, group_asts, agg_calls, ctes)
            if spec.having is not None:
                self._plan_predicate(builder, spec.having, ctes)

        # -- window functions (evaluated after aggregation, like Trino's
        # WindowNode above the AggregationNode) --
        window_calls: List[ast.WindowCall] = []
        for e in select_exprs + [s.expr for s in order_by]:
            for c in _find_window_calls(e):
                if c not in window_calls:
                    window_calls.append(c)
        if window_calls:
            self._plan_windows(builder, window_calls)

        # -- subqueries in the SELECT list / ORDER BY: scalar subqueries
        # join in, EXISTS/IN become mark-join boolean channels --
        for e in select_exprs + [s.expr for s in order_by]:
            self._plan_embedded_subqueries(builder, e, ctes)

        # -- select projection (+ hidden order-by channels) --
        conv = builder.converter()
        out_exprs = [conv.convert(e) for e in select_exprs]
        names = [self._output_name(it, i) for i, it in enumerate(select_items)]

        sort_keys: List[SortKey] = []
        hidden = 0
        for s in order_by:
            ch = self._order_by_channel(s.expr, select_items, select_exprs, names)
            if ch is None:
                out_exprs.append(conv.convert(s.expr))
                ch = len(out_exprs) - 1
                hidden += 1
            desc = s.descending
            nf = s.nulls_first if s.nulls_first is not None else desc
            sort_keys.append(SortKey(ch, desc, nf))

        fields = tuple(
            P.Field(names[i] if i < len(names) else None, e.type)
            for i, e in enumerate(out_exprs)
        )
        node: P.PlanNode = P.ProjectNode(builder.node, tuple(out_exprs), fields)

        if spec.distinct:
            if hidden:
                raise AnalysisError("DISTINCT with non-selected ORDER BY expression")
            node = P.AggregateNode(node, tuple(range(len(fields))), (), fields)

        if sort_keys:
            if limit is not None and offset == 0:
                node = P.TopNNode(node, tuple(sort_keys), limit, node.fields)
            else:
                node = P.SortNode(node, tuple(sort_keys), node.fields)
                if limit is not None or offset:
                    node = P.LimitNode(node, limit, offset, node.fields)
        elif limit is not None or offset:
            node = P.LimitNode(node, limit, offset, node.fields)

        if hidden:
            keep = tuple(range(len(names)))
            kept_fields = tuple(node.fields[i] for i in keep)
            node = P.ProjectNode(
                node,
                tuple(ir.InputRef(i, node.fields[i].type) for i in keep),
                kept_fields,
            )

        out_scope = Scope([ScopeField(None, f.name, f.type) for f in node.fields])
        return node, out_scope, names

    # ---- FROM/WHERE with join ordering ----
    def _plan_from_where(
        self, spec: ast.QuerySpec, ctes
    ) -> Tuple[Builder, List[ast.Expression]]:
        conjunct_pool: List[ast.Expression] = []
        where_conjuncts = split_conjuncts(spec.where)
        for c in where_conjuncts:
            conjunct_pool.extend(_common_or_conjuncts(c))
        conjunct_pool.extend(where_conjuncts)

        if spec.from_ is None:
            node = P.ValuesNode((P.Field("dummy", T.BIGINT),), ((0,),))
            b = Builder(node, Scope([ScopeField(None, None, T.BIGINT)]))
            return b, conjunct_pool

        items: List[RelationItem] = []
        self._collect_relations(spec.from_, items, conjunct_pool, ctes)
        items, decl_segments = self._resolve_lateral_unnests(items)

        # classify conjuncts
        leftovers: List[ast.Expression] = []
        item_filters: Dict[int, List[ast.Expression]] = {i: [] for i in range(len(items))}
        join_edges: List[Tuple[int, int, ast.Identifier, ast.Identifier]] = []
        seen: Set[int] = set()
        for c in conjunct_pool:
            if id(c) in seen:
                continue
            seen.add(id(c))
            if _has_subquery(c):
                leftovers.append(c)
                continue
            owners = self._items_of(c, items)
            if owners is None:
                leftovers.append(c)  # references outer scope etc.
                continue
            if len(owners) == 1:
                item_filters[next(iter(owners))].append(c)
                continue
            edge = self._equi_edge(c, items)
            if edge is not None:
                join_edges.append(edge)
            else:
                leftovers.append(c)

        # apply single-item filters (predicate pushdown)
        for i, item in enumerate(items):
            if item_filters[i]:
                conv = ExprConverter(item.scope)
                pred = ir.and_(*[conv.convert(c) for c in item_filters[i]])
                item.node = P.FilterNode(item.node, pred, item.node.fields)
                item.rows = max(item.rows / 3.0, 1.0)

        # greedy join-order assembly
        joined = [0]
        current = items[0]
        current_offsets = {0: 0}
        pending_edges = list(join_edges)
        while len(joined) < len(items):
            # pick a connected item (smallest) else smallest remaining
            candidates: Dict[int, List] = {}
            for e in pending_edges:
                a, b_, _, _ = e
                if (a in joined) != (b_ in joined):
                    new = b_ if a in joined else a
                    candidates.setdefault(new, []).append(e)
            if candidates:
                new = min(candidates, key=lambda i: items[i].rows)
                edges = candidates[new]
            else:
                remaining = [i for i in range(len(items)) if i not in joined]
                new = min(remaining, key=lambda i: items[i].rows)
                edges = []
            current, current_offsets = self._join_items(
                current, current_offsets, items, new, edges
            )
            joined.append(new)
            pending_edges = [e for e in pending_edges if e not in edges]

        # restore FROM declaration order: greedy assembly (and the
        # build/probe swap in _join_items) concatenates scopes in join
        # order, but SELECT * and positional semantics follow the FROM
        # clause — re-project when the two differ
        perm: List[int] = []
        for pi, lo, hi in decl_segments:
            base = current_offsets[pi]
            perm.extend(range(base + lo, base + hi))
        if perm != list(range(len(current.scope.fields))):
            fields = tuple(current.node.fields[c] for c in perm)
            exprs = tuple(
                ir.InputRef(c, current.node.fields[c].type) for c in perm
            )
            node = P.ProjectNode(current.node, exprs, fields)
            scope = Scope([current.scope.fields[c] for c in perm])
            current = RelationItem(node, scope, current.rows)
        builder = Builder(current.node, current.scope)
        # any pending equi edges not used as keys become filters
        for a, b_, ia, ib in pending_edges:
            leftovers.append(ast.BinaryOp("eq", ia, ib))
        return builder, leftovers

    def _join_items(self, current, offsets, items, new_idx, edges):
        """Hash-join `current` (accumulated) with items[new_idx]; smaller
        side becomes the build side (the CostCalculator-lite rule)."""
        new = items[new_idx]
        cur_keys: List[int] = []
        new_keys: List[int] = []
        for a, b_, ia, ib in edges:
            if a in offsets:
                cur_ident, new_ident = ia, ib
            else:
                cur_ident, new_ident = ib, ia
            cur_keys.append(current.scope.resolve(cur_ident.parts)[0])
            new_keys.append(new.scope.resolve(new_ident.parts)[0])
        if not edges:
            # cross join: build = new side
            node = P.JoinNode(
                "cross", current.node, new.node, (), (), None,
                current.node.fields + new.node.fields,
            )
            scope = Scope.concat(current.scope, new.scope)
            item = RelationItem(node, scope, current.rows * max(new.rows, 1.0))
            offsets = dict(offsets)
            offsets[new_idx] = len(current.scope)
            return item, offsets
        if new.rows <= current.rows:
            # probe = current, build = new
            node = P.JoinNode(
                "inner", current.node, new.node,
                tuple(cur_keys), tuple(new_keys), None,
                current.node.fields + new.node.fields,
            )
            scope = Scope.concat(current.scope, new.scope)
            offsets = dict(offsets)
            offsets[new_idx] = len(current.scope)
        else:
            # probe = new, build = current (swap sides)
            node = P.JoinNode(
                "inner", new.node, current.node,
                tuple(new_keys), tuple(cur_keys), None,
                new.node.fields + current.node.fields,
            )
            scope = Scope.concat(new.scope, current.scope)
            shift = len(new.scope)
            offsets = {k: v + shift for k, v in offsets.items()}
            offsets[new_idx] = 0
        rows = max(current.rows, new.rows)
        return RelationItem(node, scope, rows), offsets

    def _items_of(self, e: ast.Expression, items) -> Optional[Set[int]]:
        owners: Set[int] = set()
        for ident in _idents(e):
            hit = None
            for i, item in enumerate(items):
                r = item.scope.try_resolve(ident.parts)
                if r is not None:
                    if hit is not None:
                        raise AnalysisError(f"column '{ident}' is ambiguous")
                    hit = i
            if hit is None:
                return None  # outer reference or unknown
            owners.add(hit)
        return owners

    def _equi_edge(self, c, items):
        if not (isinstance(c, ast.BinaryOp) and c.op == "eq"):
            return None
        if not (isinstance(c.left, ast.Identifier) and isinstance(c.right, ast.Identifier)):
            return None
        la = self._items_of(c.left, items)
        ra = self._items_of(c.right, items)
        if la is None or ra is None or len(la) != 1 or len(ra) != 1:
            return None
        a, b = next(iter(la)), next(iter(ra))
        if a == b:
            return None
        return (a, b, c.left, c.right)

    def _collect_relations(self, rel: ast.Relation, items, conjunct_pool, ctes):
        if isinstance(rel, ast.UnnestRelation) and all(
            isinstance(a, ast.Identifier) for a in rel.arrays
        ):
            # lateral UNNEST over columns of a sibling relation:
            # deferred until every FROM item is planned
            # (_resolve_lateral_unnests)
            items.append(_DeferredUnnest(rel))
            return
        if isinstance(rel, ast.Join):
            if rel.kind == "cross":
                self._collect_relations(rel.left, items, conjunct_pool, ctes)
                self._collect_relations(rel.right, items, conjunct_pool, ctes)
                return
            if rel.kind == "inner":
                self._collect_relations(rel.left, items, conjunct_pool, ctes)
                self._collect_relations(rel.right, items, conjunct_pool, ctes)
                if rel.condition is not None:
                    conjunct_pool.extend(split_conjuncts(rel.condition))
                for col in rel.using:
                    raise AnalysisError("USING not yet supported")
                return
            # outer joins: plan as one composite item
            items.append(self._plan_outer_join(rel, ctes))
            return
        items.append(self._plan_relation_leaf(rel, ctes))

    def _plan_outer_join(self, rel: ast.Join, ctes) -> RelationItem:
        left_items: List[RelationItem] = []
        pool: List[ast.Expression] = []
        self._collect_relations(rel.left, left_items, pool, ctes)
        if len(left_items) == 1 and not pool:
            left = left_items[0]
        else:
            # composite left side (a join tree feeding the outer join —
            # the q72 shape): assemble it with the shared greedy-join
            # machinery, leftovers become pre-join filters
            lb, leftovers = self._assemble_items(left_items, pool)
            for c in leftovers:
                lb.filter(ExprConverter(lb.scope).convert(c))
            left = RelationItem(lb.node, lb.scope, 1000.0)
        right = self._plan_relation_leaf_any(rel.right, ctes)
        swapped = rel.kind == "right"
        if swapped:
            # RIGHT join plans as LEFT with sides swapped (the reference
            # does the same in RelationPlanner); the output projection
            # below restores declared column order
            left, right = right, left
        lkeys: List[int] = []
        rkeys: List[int] = []
        residuals: List[ast.Expression] = []
        for c in split_conjuncts(rel.condition):
            if isinstance(c, ast.BinaryOp) and c.op == "eq" and isinstance(
                c.left, ast.Identifier
            ) and isinstance(c.right, ast.Identifier):
                l_hit = left.scope.try_resolve(c.left.parts)
                r_hit = right.scope.try_resolve(c.right.parts)
                if l_hit is not None and r_hit is not None:
                    lkeys.append(l_hit[0])
                    rkeys.append(r_hit[0])
                    continue
                l_hit2 = left.scope.try_resolve(c.right.parts)
                r_hit2 = right.scope.try_resolve(c.left.parts)
                if l_hit2 is not None and r_hit2 is not None:
                    lkeys.append(l_hit2[0])
                    rkeys.append(r_hit2[0])
                    continue
            residuals.append(c)
        residual_ir = None
        if residuals:
            conv = ExprConverter(Scope.concat(left.scope, right.scope))
            residual_ir = ir.and_(*[conv.convert(c) for c in residuals])
        kind = "full" if rel.kind == "full" else "left"
        node = P.JoinNode(
            kind, left.node, right.node, tuple(lkeys), tuple(rkeys),
            residual_ir, left.node.fields + right.node.fields,
        )
        item = RelationItem(
            node, Scope.concat(left.scope, right.scope), max(left.rows, right.rows)
        )
        if swapped:
            # restore declared column order (probe side was moved left)
            w_r = len(left.scope.fields)  # right relation is now probe
            perm = list(range(w_r, w_r + len(right.scope.fields))) + list(
                range(w_r)
            )
            exprs = tuple(
                ir.InputRef(c, node.fields[c].type) for c in perm
            )
            fields = tuple(node.fields[c] for c in perm)
            scope = Scope([item.scope.fields[c] for c in perm])
            item = RelationItem(
                P.ProjectNode(node, exprs, fields), scope, item.rows
            )
        return item

    def _plan_relation_leaf_any(self, rel, ctes) -> RelationItem:
        items: List[RelationItem] = []
        pool: List[ast.Expression] = []
        self._collect_relations(rel, items, pool, ctes)
        # single-item requirement => segments are always in order here
        items, _ = self._resolve_lateral_unnests(items)
        if len(items) != 1 or pool:
            raise AnalysisError("nested join tree not yet supported here")
        return items[0]

    def _resolve_lateral_unnests(self, items):
        """Fold _DeferredUnnest markers (UNNEST over column references,
        `FROM t, UNNEST(t.arr)`) into their source items as UnnestNodes
        — the reference's correlated-unnest planning
        (RelationPlanner.planJoinUnnest).

        Returns (physical_items, segments): `segments` lists, in FROM
        declaration order, (physical_idx, field_lo, field_hi) ranges so
        the caller can re-project the assembled join back to declaration
        order — the unnest's columns belong at the MARKER's position in
        SELECT *, not at the end of its owner's columns."""
        out = [it for it in items if not isinstance(it, _DeferredUnnest)]
        # declaration-ordered slots; marker slots are patched as folded
        segments: List = []
        slot_of_marker: Dict[int, int] = {}
        phys = 0
        for i, it in enumerate(items):
            if isinstance(it, _DeferredUnnest):
                slot_of_marker[i] = len(segments)
                segments.append(None)
            else:
                segments.append((phys, 0, len(it.scope.fields)))
                phys += 1
        markers = [
            (i, it) for i, it in enumerate(items)
            if isinstance(it, _DeferredUnnest)
        ]
        if not markers:
            return items, segments
        for marker_pos, marker in markers:
            rel = marker.rel
            # locate the single source item owning every referenced column
            owner_idx = None
            channels: List[int] = []
            elem_types: List[T.DataType] = []
            for e in rel.arrays:
                hit = None
                for j, it in enumerate(out):
                    r = it.scope.try_resolve(e.parts)
                    if r is not None:
                        if hit is not None:
                            raise AnalysisError(
                                f"UNNEST argument '{e}' is ambiguous"
                            )
                        hit = (j, r[0], r[1])
                if hit is None:
                    raise AnalysisError(
                        f"UNNEST argument '{e}' not found (constant"
                        " arrays and array columns are supported)"
                    )
                j, ch, t = hit
                if not t.is_array:
                    raise AnalysisError(
                        f"UNNEST argument '{e}' is {t}, not an array"
                    )
                if owner_idx is None:
                    owner_idx = j
                elif owner_idx != j:
                    raise AnalysisError(
                        "UNNEST arguments must come from one relation"
                    )
                channels.append(ch)
                elem_types.append(t.element)
            src = out[owner_idx]
            n_new = len(channels) + (1 if rel.ordinality else 0)
            names = list(rel.column_aliases) if rel.column_aliases else [
                f"_col{i}" for i in range(n_new)
            ]
            if len(names) != n_new:
                raise AnalysisError(
                    f"UNNEST alias has {len(names)} columns,"
                    f" produces {n_new}"
                )
            new_fields = [
                P.Field(nm, t) for nm, t in zip(names, elem_types)
            ]
            if rel.ordinality:
                new_fields.append(P.Field(names[-1], T.BIGINT))
            node = P.UnnestNode(
                src.node,
                tuple(channels),
                rel.ordinality,
                src.node.fields + tuple(new_fields),
            )
            scope = Scope(
                src.scope.fields
                + [
                    ScopeField(rel.alias, f.name, f.type)
                    for f in new_fields
                ]
            )
            w_before = len(src.scope.fields)
            segments[slot_of_marker[marker_pos]] = (
                owner_idx, w_before, w_before + len(new_fields)
            )
            out[owner_idx] = RelationItem(node, scope, src.rows * 3.0)
        return out, segments

    def _plan_relation_leaf(self, rel: ast.Relation, ctes) -> RelationItem:
        if isinstance(rel, ast.TableRef):
            name = rel.name
            if len(name) == 1 and name[0] in ctes:
                w = ctes[name[0]]
                inner_ctes = {k: v for k, v in ctes.items() if k != name[0]}
                node, scope, names = self.plan_query(w.query, inner_ctes)
                out_names = list(w.column_names) if w.column_names else names
                qual = rel.alias or name[0]
                sc = Scope(
                    [
                        ScopeField(qual, n, f.type)
                        for n, f in zip(out_names, node.fields)
                    ]
                )
                return RelationItem(node, sc, 1000.0)
            return self._plan_table(rel)
        if isinstance(rel, ast.UnnestRelation):
            return self._plan_unnest(rel)
        if isinstance(rel, ast.TableFunctionRelation):
            return self._plan_table_function(rel, ctes)
        if isinstance(rel, ast.MatchRecognizeRelation):
            return self._plan_match_recognize(rel, ctes)
        if isinstance(rel, ast.SubqueryRelation):
            node, scope, names = self.plan_query(rel.query, ctes)
            if rel.column_aliases:
                if len(rel.column_aliases) != len(node.fields):
                    raise AnalysisError(
                        f"column alias list has {len(rel.column_aliases)} "
                        f"names but relation has {len(node.fields)} columns"
                    )
                names = list(rel.column_aliases)
            sc = Scope(
                [ScopeField(rel.alias, n, f.type) for n, f in zip(names, node.fields)]
            )
            return RelationItem(node, sc, 1000.0)
        raise AnalysisError(f"unsupported relation {type(rel).__name__}")

    def _plan_unnest(self, rel: ast.UnnestRelation) -> RelationItem:
        """UNNEST over constant arrays (ARRAY[...] literals and
        sequence(...)) — the UnnestOperator's surface
        (main/operator/unnest/UnnestOperator.java) for the array values
        this engine can hold; array-typed COLUMNS need the nested
        column representation (offsets + flat values), planned work.
        Multiple arrays zip positionally, short ones padded with NULL
        (Trino's multi-argument UNNEST semantics)."""
        columns = []
        for e in rel.arrays:
            vals = _const_array_values(e)
            if vals is None:
                raise AnalysisError(
                    "UNNEST supports constant arrays (ARRAY[...] /"
                    " sequence(...)); array-typed columns are not yet"
                    " representable"
                )
            columns.append(vals)
        n = max((len(c) for c in columns), default=0)
        col_types = [_array_element_type(c) for c in columns]
        rows = []
        for i in range(n):
            row = [
                (c[i].value if i < len(c) else None) for c in columns
            ]
            if rel.ordinality:
                row.append(i + 1)
            rows.append(tuple(row))
        if rel.ordinality:
            col_types.append(T.BIGINT)
        names = list(rel.column_aliases) if rel.column_aliases else [
            f"_col{i}" for i in range(len(col_types))
        ]
        if len(names) != len(col_types):
            raise AnalysisError(
                f"UNNEST alias has {len(names)} columns, produces {len(col_types)}"
            )
        fields = tuple(P.Field(nm, t) for nm, t in zip(names, col_types))
        node = P.ValuesNode(fields, tuple(rows))
        scope = Scope(
            [ScopeField(rel.alias, nm, t) for nm, t in zip(names, col_types)]
        )
        return RelationItem(node, scope, float(max(n, 1)))

    @staticmethod
    def _pattern_vars(node) -> Set[str]:
        return _pattern_var_names(node)

    def _plan_match_recognize(
        self, rel: ast.MatchRecognizeRelation, ctes
    ) -> RelationItem:
        """Row pattern recognition (StatementAnalyzer's
        analyzePatternRecognition — SURVEY.md §2.6). Supported subset:
        ONE ROW PER MATCH; DEFINE conditions over current-row columns
        and PREV/NEXT(col [, n]) (vectorized as shifted columns —
        references to OTHER variables' rows, e.g. LAST(A.price) inside
        DEFINE, need running match state and are rejected); measures
        FIRST/LAST(var.col), var.col, MATCH_NUMBER(), CLASSIFIER()."""
        if rel.rows_per_match != "one":
            raise AnalysisError(
                "only ONE ROW PER MATCH is supported"
            )
        item = self._plan_relation_leaf_any(rel.input, ctes)
        scope = item.scope
        pattern_vars = _pattern_var_names(rel.pattern)
        define_vars = {v.lower() for v, _ in rel.defines}
        for v in define_vars:
            if v not in pattern_vars:
                raise AnalysisError(
                    f"DEFINE variable '{v}' does not appear in PATTERN"
                )

        def channel_of(e: ast.Expression) -> int:
            if not isinstance(e, ast.Identifier):
                raise AnalysisError(
                    "MATCH_RECOGNIZE partition/order items must be columns"
                )
            return scope.resolve(e.parts)[0]

        partition_channels = tuple(channel_of(e) for e in rel.partition_by)
        order_keys = tuple(
            SortKey(channel_of(s.expr), s.descending)
            for s in rel.order_by
        )
        # -- DEFINE conditions -> ir over the extended schema --
        shifts: List[Tuple[int, int]] = []  # (channel, roll offset)
        shift_index: Dict[Tuple[int, int], int] = {}
        base_width = len(scope.fields)

        def shifted_field(ch: int, off: int) -> ast.Identifier:
            key = (ch, off)
            if key not in shift_index:
                shift_index[key] = len(shifts)
                shifts.append(key)
            return ast.Identifier((f"__shift{shift_index[key]}",))

        def rewrite(e: ast.Expression, var: str) -> ast.Expression:
            if isinstance(e, ast.Identifier):
                if len(e.parts) == 2 and e.parts[0].lower() in pattern_vars:
                    if e.parts[0].lower() != var:
                        raise AnalysisError(
                            f"DEFINE {var.upper()}: references to other"
                            f" variables' rows ({e.parts[0]}.{e.parts[1]})"
                            " are not supported — use PREV/NEXT navigation"
                        )
                    return ast.Identifier((e.parts[1],))
                return e
            if isinstance(e, ast.FunctionCall) and e.name.lower() in (
                "prev", "next"
            ):
                if not e.args or not isinstance(e.args[0], ast.Identifier):
                    raise AnalysisError(
                        f"{e.name}() supports a column reference argument"
                    )
                inner = rewrite(e.args[0], var)
                ch = scope.resolve(inner.parts)[0]
                n = 1
                if len(e.args) > 1:
                    if not isinstance(e.args[1], ast.NumberLiteral):
                        raise AnalysisError(
                            f"{e.name}() offset must be a number literal"
                        )
                    n = int(e.args[1].text)
                off = n if e.name.lower() == "prev" else -n
                return shifted_field(ch, off)
            # rebuild recursively over dataclass fields
            import dataclasses as _dc

            if _dc.is_dataclass(e) and isinstance(e, ast.Node):
                changes = {}
                for f in _dc.fields(e):
                    v = getattr(e, f.name)
                    if isinstance(v, ast.Expression):
                        changes[f.name] = rewrite(v, var)
                    elif isinstance(v, tuple) and v and isinstance(
                        v[0], ast.Expression
                    ):
                        changes[f.name] = tuple(rewrite(x, var) for x in v)
                if changes:
                    return _dc.replace(e, **changes)
            return e

        # conversions happen against an extended scope that appends one
        # pseudo-column per distinct (channel, offset)
        defines_ir: List[Tuple[str, ir.Expr]] = []
        rewritten = [
            (v.lower(), rewrite(cond, v.lower())) for v, cond in rel.defines
        ]
        ext_fields = list(scope.fields)
        for i, (ch, _off) in enumerate(shifts):
            ext_fields.append(
                ScopeField(None, f"__shift{i}", scope.fields[ch].type)
            )
        ext_scope = Scope(ext_fields)
        for v, cond in rewritten:
            conv = ExprConverter(ext_scope)
            pred = conv.convert(cond)
            if pred.type.kind != T.TypeKind.BOOLEAN:
                raise AnalysisError(
                    f"DEFINE {v.upper()} must be a boolean condition"
                )
            defines_ir.append((v, pred))
        # -- measures --
        measures: List[P.MeasureSpec] = []
        for mi in rel.measures:
            e = mi.expr
            if isinstance(e, ast.FunctionCall) and e.name.lower() in (
                "match_number", "classifier"
            ):
                kind = e.name.lower()
                measures.append(P.MeasureSpec(
                    kind, mi.name,
                    T.BIGINT if kind == "match_number" else T.VARCHAR,
                ))
                continue
            kind = "last"
            if isinstance(e, ast.FunctionCall) and e.name.lower() in (
                "first", "last"
            ):
                kind = e.name.lower()
                if len(e.args) != 1:
                    raise AnalysisError(f"{e.name}() takes one argument")
                e = e.args[0]
            if not isinstance(e, ast.Identifier):
                raise AnalysisError(
                    "measures support FIRST/LAST(var.col), var.col,"
                    " MATCH_NUMBER() and CLASSIFIER()"
                )
            var = None
            parts = e.parts
            if len(parts) == 2 and parts[0].lower() in pattern_vars:
                var = parts[0].lower()
                parts = (parts[1],)
            ch, t = scope.resolve(parts)
            measures.append(P.MeasureSpec(kind, mi.name, t, var, ch))
        # -- output schema: partition columns + measures --
        out_fields: List[P.Field] = []
        out_scope_fields: List[ScopeField] = []
        for ch in partition_channels:
            f = scope.fields[ch]
            out_fields.append(P.Field(f.name, f.type))
            out_scope_fields.append(ScopeField(rel.alias, f.name, f.type))
        for m in measures:
            out_fields.append(P.Field(m.name, m.out_type))
            out_scope_fields.append(
                ScopeField(rel.alias, m.name, m.out_type)
            )
        node = P.MatchRecognizeNode(
            item.node,
            partition_channels,
            order_keys,
            tuple(defines_ir),
            tuple(shifts),
            rel.pattern,
            tuple(measures),
            rel.after_match,
            tuple(out_fields),
        )
        return RelationItem(node, Scope(out_scope_fields), item.rows / 4.0)

    def _plan_table_function(
        self, rel: ast.TableFunctionRelation, ctes
    ) -> RelationItem:
        """FROM TABLE(fn(...)) — polymorphic table functions
        (spi/ptf/ConnectorTableFunction.java surface). Built-ins
        `sequence` and `exclude_columns` are engine-side
        (the reference's io.trino.operator.table.Sequence /
        ExcludeColumns); other names resolve to the connector's
        TableFunction registry and evaluate at plan time over literal
        arguments."""
        fn_name = rel.name[-1].lower()
        # assemble arguments: positional list + named dict
        named: Dict[str, ast.Expression] = {
            k.lower(): v for k, v in rel.named_args
        }

        def scalar(e) -> object:
            if e is None:
                raise AnalysisError(
                    f"table function {fn_name}(): missing required argument"
                )
            conv = ExprConverter(Scope([]))
            lit = conv.convert(e)
            if not isinstance(lit, ir.Literal):
                raise AnalysisError(
                    f"table function {fn_name}() arguments must be"
                    " constants"
                )
            return lit.value

        if fn_name == "sequence" and len(rel.name) == 1:
            args = list(rel.args)
            start = scalar(named.get("start", args[0] if args else None))
            stop = scalar(named.get("stop", args[1] if len(args) > 1 else None))
            step_e = named.get("step", args[2] if len(args) > 2 else None)
            step = scalar(step_e) if step_e is not None else 1
            if step == 0:
                raise AnalysisError("sequence() step must not be zero")
            start, stop, step = int(start), int(stop), int(step)
            count = max(0, (stop - start) // step + 1)
            if count > 10_000_000:
                # plan-time materialization cap (the reference streams
                # this function; a runaway range must not OOM analysis)
                raise AnalysisError(
                    f"sequence() would produce {count} rows"
                    " (limit 10000000)"
                )
            vals = list(range(start, stop + (1 if step > 0 else -1), step))
            names = list(rel.column_aliases) or ["sequential_number"]
            fields = (P.Field(names[0], T.BIGINT),)
            node = P.ValuesNode(fields, tuple((v,) for v in vals))
            scope = Scope([ScopeField(rel.alias, names[0], T.BIGINT)])
            return RelationItem(node, scope, float(max(len(vals), 1)))
        if fn_name == "exclude_columns" and len(rel.name) == 1:
            args = list(rel.args)
            tbl = named.get("input", args[0] if args else None)
            desc = named.get("columns", args[1] if len(args) > 1 else None)
            if not isinstance(tbl, ast.TableArg) or not isinstance(
                desc, ast.Descriptor
            ):
                raise AnalysisError(
                    "exclude_columns(input => TABLE(...), columns =>"
                    " DESCRIPTOR(...))"
                )
            item = self._plan_relation_leaf_any(tbl.relation, ctes)
            drop = {n.lower() for n in desc.names}
            keep = [
                (i, f)
                for i, f in enumerate(item.scope.fields)
                if (f.name or "").lower() not in drop
            ]
            missing = drop - {
                (f.name or "").lower() for f in item.scope.fields
            }
            if missing:
                raise AnalysisError(
                    f"exclude_columns: no such columns {sorted(missing)}"
                )
            if not keep:
                raise AnalysisError("exclude_columns removed every column")
            exprs = tuple(ir.InputRef(i, f.type) for i, f in keep)
            fields = tuple(
                P.Field(f.name, f.type) for _, f in keep
            )
            node = P.ProjectNode(item.node, exprs, fields)
            scope = Scope(
                [ScopeField(rel.alias, f.name, f.type) for _, f in keep]
            )
            return RelationItem(node, scope, item.rows)
        # connector-provided table function
        catalog = rel.name[0] if len(rel.name) > 1 else self.catalog
        try:
            conn = self.catalogs.get(catalog)
        except KeyError:
            raise AnalysisError(f"unknown catalog '{catalog}'")
        tf = conn.table_functions.get(fn_name)
        if tf is None:
            raise AnalysisError(
                f"unknown table function {'.'.join(rel.name)}()"
            )
        call_args = {k: scalar(v) for k, v in named.items()}
        for i, a in enumerate(rel.args):
            call_args[f"_{i}"] = scalar(a)
        columns, rows = tf.fn(call_args)
        names = (
            list(rel.column_aliases)
            if rel.column_aliases
            else [c.name for c in columns]
        )
        if len(names) != len(columns):
            raise AnalysisError(
                f"alias has {len(names)} columns, function produces"
                f" {len(columns)}"
            )
        fields = tuple(
            P.Field(nm, c.type) for nm, c in zip(names, columns)
        )
        node = P.ValuesNode(fields, tuple(tuple(r) for r in rows))
        scope = Scope(
            [
                ScopeField(rel.alias, nm, c.type)
                for nm, c in zip(names, columns)
            ]
        )
        return RelationItem(node, scope, float(max(len(rows), 1)))

    def _plan_table(self, rel: ast.TableRef) -> RelationItem:
        parts = rel.name
        if len(parts) == 1:
            catalog, schema, table = self.catalog, self.schema, parts[0]
        elif len(parts) == 2:
            catalog, schema, table = self.catalog, parts[0], parts[1]
        else:
            catalog, schema, table = parts
        conn, handle = self.catalogs.resolve_table(catalog, schema, table)
        meta = conn.metadata.get_table_metadata(handle)
        columns = tuple(c.name for c in meta.columns)
        fields = tuple(P.Field(c.name, c.type) for c in meta.columns)
        node = P.ScanNode(catalog, handle, columns, fields)
        qual = rel.alias or table
        scope = Scope([ScopeField(qual, c.name, c.type) for c in meta.columns])
        stats = conn.metadata.get_table_statistics(handle)
        rows = stats.row_count or 1000.0
        return RelationItem(node, scope, rows)

    # ---- predicates with subqueries ----
    def _plan_predicate(self, builder: Builder, e: ast.Expression, ctes) -> None:
        for conj in split_conjuncts(e):
            if isinstance(conj, ast.Exists):
                self._plan_exists(builder, conj.query, conj.negated, ctes)
                continue
            if (
                isinstance(conj, ast.UnaryOp)
                and conj.op == "not"
                and isinstance(conj.operand, ast.Exists)
            ):
                self._plan_exists(builder, conj.operand.query, True, ctes)
                continue
            if isinstance(conj, ast.InSubquery):
                self._plan_in_subquery(builder, conj, ctes)
                continue
            # general positions: EXISTS/IN under OR or NOT, scalar
            # subqueries anywhere in the conjunct — mark joins +
            # replacement channels
            self._plan_embedded_subqueries(builder, conj, ctes)
            pred = builder.converter().convert(conj)
            builder.filter(pred)

    def _plan_exists(self, builder: Builder, q: ast.Query, negated: bool, ctes) -> None:
        if not isinstance(q.body, ast.QuerySpec) or q.body.group_by or q.with_:
            raise AnalysisError("EXISTS subquery too complex")
        spec = q.body
        inner_items: List[RelationItem] = []
        pool: List[ast.Expression] = []
        self._collect_relations(spec.from_, inner_items, pool, ctes)
        pool.extend(split_conjuncts(spec.where))
        (
            inner,
            probe_keys,
            build_keys,
            residuals,
        ) = self._decorrelate(builder, inner_items, pool)
        residual_ir = None
        if residuals:
            conv = ExprConverter(Scope.concat(builder.scope, inner.scope))
            residual_ir = ir.and_(*[conv.convert(c) for c in residuals])
        kind = "anti" if negated else "semi"
        builder.node = P.JoinNode(
            kind, builder.node, inner.node,
            tuple(probe_keys), tuple(build_keys), residual_ir, builder.node.fields,
        )
        # scope unchanged: semi/anti output = probe columns

    def _decorrelate(self, builder: Builder, inner_items, pool,
                     filter_outer: bool = True):
        """Assemble the subquery side and split its conjuncts into inner
        filters / correlation equi keys / cross-scope residuals.

        `filter_outer=False` (mark joins): outer-only conjuncts become
        RESIDUALS instead of filters on the outer query — a mark join
        must preserve outer cardinality, so an outer-only predicate may
        only flip match flags, never delete outer rows."""
        inner_filters: List[ast.Expression] = []
        corr_pairs: List[Tuple[ast.Identifier, ast.Identifier]] = []
        residuals: List[ast.Expression] = []
        inner_scope_probe = Scope(
            [f for it in inner_items for f in it.scope.fields]
        )
        for c in pool:
            if _has_subquery(c):
                raise AnalysisError("nested subquery inside EXISTS not supported")
            refs_inner = refs_outer = False
            for ident in _idents(c):
                if inner_scope_probe.try_resolve(ident.parts) is not None:
                    refs_inner = True
                elif builder.scope.try_resolve(ident.parts) is not None:
                    refs_outer = True
                else:
                    raise AnalysisError(f"cannot resolve {ident}")
            if refs_outer and not refs_inner:
                if filter_outer:
                    # conjunct-position EXISTS: outer-only predicate
                    # inside the subquery filters the outer query
                    self._plan_predicate(builder, c, {})
                else:
                    residuals.append(c)
                continue
            if not refs_outer:
                inner_filters.append(c)
                continue
            if (
                isinstance(c, ast.BinaryOp)
                and c.op == "eq"
                and isinstance(c.left, ast.Identifier)
                and isinstance(c.right, ast.Identifier)
            ):
                l_inner = inner_scope_probe.try_resolve(c.left.parts)
                r_inner = inner_scope_probe.try_resolve(c.right.parts)
                if l_inner is None and r_inner is not None:
                    corr_pairs.append((c.left, c.right))
                    continue
                if r_inner is None and l_inner is not None:
                    corr_pairs.append((c.right, c.left))
                    continue
            residuals.append(c)

        # assemble the inner side with its own greedy join order
        inner_builder, inner_leftovers = self._assemble_items(
            inner_items, inner_filters
        )
        for c in inner_leftovers:
            pred = ExprConverter(inner_builder.scope).convert(c)
            inner_builder.filter(pred)
        inner = RelationItem(inner_builder.node, inner_builder.scope, 0.0)
        probe_keys = [builder.scope.resolve(o.parts)[0] for o, _ in corr_pairs]
        build_keys = [inner.scope.resolve(i.parts)[0] for _, i in corr_pairs]
        return inner, probe_keys, build_keys, residuals

    def _assemble_items(self, items, conjuncts) -> Tuple[Builder, List[ast.Expression]]:
        """Greedy-join a prepared item list with a conjunct pool (shared
        by FROM planning and subquery decorrelation)."""
        spec_like_pool = list(conjuncts)
        leftovers: List[ast.Expression] = []
        item_filters: Dict[int, List[ast.Expression]] = {
            i: [] for i in range(len(items))
        }
        join_edges = []
        for c in spec_like_pool:
            owners = self._items_of(c, items)
            if owners is None:
                leftovers.append(c)
                continue
            if len(owners) == 1:
                item_filters[next(iter(owners))].append(c)
                continue
            edge = self._equi_edge(c, items)
            if edge is not None:
                join_edges.append(edge)
            else:
                leftovers.append(c)
        for i, item in enumerate(items):
            if item_filters[i]:
                conv = ExprConverter(item.scope)
                pred = ir.and_(*[conv.convert(c) for c in item_filters[i]])
                item.node = P.FilterNode(item.node, pred, item.node.fields)
                item.rows = max(item.rows / 3.0, 1.0)
        joined = [0]
        current = items[0]
        offsets = {0: 0}
        pending = list(join_edges)
        while len(joined) < len(items):
            candidates: Dict[int, List] = {}
            for e in pending:
                a, b_, _, _ = e
                if (a in joined) != (b_ in joined):
                    new = b_ if a in joined else a
                    candidates.setdefault(new, []).append(e)
            if candidates:
                new = min(candidates, key=lambda i: items[i].rows)
                edges = candidates[new]
            else:
                remaining = [i for i in range(len(items)) if i not in joined]
                new = min(remaining, key=lambda i: items[i].rows)
                edges = []
            current, offsets = self._join_items(current, offsets, items, new, edges)
            joined.append(new)
            pending = [e for e in pending if e not in edges]
        return Builder(current.node, current.scope), leftovers

    def _plan_in_subquery(self, builder: Builder, conj: ast.InSubquery, ctes) -> None:
        node, scope, _ = self.plan_query(conj.query, ctes)
        if len(node.fields) != 1:
            raise AnalysisError("IN subquery must return one column")
        value = conj.value
        if not isinstance(value, ast.Identifier):
            raise AnalysisError("IN (subquery) value must be a column")
        probe_ch, probe_t = builder.scope.resolve(value.parts)
        if not conj.negated:
            builder.node = P.JoinNode(
                "semi", builder.node, node, (probe_ch,), (0,), None,
                builder.node.fields,
            )
            return
        # NULL-aware NOT IN. `x NOT IN S` is TRUE iff x matches nothing
        # in S, S contains no NULL (one NULL makes every non-match
        # UNKNOWN), and x itself is non-NULL — EXCEPT that S being empty
        # makes the predicate TRUE for every row, NULL x included.
        # Planned as: anti join (NULL probes survive: they match
        # nothing) -> cross join with ONE scalar aggregate of S giving
        # (count(*), count(col)) -> filter
        # (count(*) = count(col)) AND (x IS NOT NULL OR count(*) = 0).
        # The shape of Trino's null-aware semi-join rewrite family.
        # NOTE: the subquery appears twice (build side AND count
        # source), so it executes twice — shared-subtree materialization
        # (CTE reuse) is the planned fix. It is PLANNED twice so the two
        # uses are distinct subtrees: node identity doubles as the plan-
        # node id, and the structure validator rejects a DAG.
        builder.node = P.JoinNode(
            "anti", builder.node, node, (probe_ch,), (0,), None,
            builder.node.fields,
        )
        sub_t = node.fields[0].type
        count_source, _, _ = self.plan_query(conj.query, ctes)
        counts = P.AggregateNode(
            count_source,
            (),
            (
                P.AggCall("count_star", None, T.BIGINT),
                P.AggCall("count", 0, T.BIGINT),
            ),
            (P.Field(None, T.BIGINT), P.Field(None, T.BIGINT)),
        )
        total_ch = len(builder.scope)
        builder.node = P.JoinNode(
            "cross", builder.node, counts, (), (), None,
            builder.node.fields + counts.fields,
        )
        builder.scope = Scope(
            builder.scope.fields
            + [ScopeField(None, None, T.BIGINT), ScopeField(None, None, T.BIGINT)]
        )
        total = ir.InputRef(total_ch, T.BIGINT)
        nonnull = ir.InputRef(total_ch + 1, T.BIGINT)
        zero = ir.Literal(0, T.BIGINT)
        builder.filter(
            ir.and_(
                ir.comparison("eq", total, nonnull),
                ir.or_(
                    ir.not_(ir.is_null(ir.InputRef(probe_ch, probe_t))),
                    ir.comparison("eq", total, zero),
                ),
            )
        )

    def _plan_embedded_subqueries(self, builder: Builder, e, ctes) -> None:
        """Plan every subquery appearing in a GENERAL position inside
        `e` (under OR/NOT, in the SELECT list, in ORDER BY): scalar
        subqueries join as before; EXISTS/IN become MARK joins whose
        boolean channel replaces the subquery expression — the
        TransformExistsApplyToCorrelatedJoin / semiJoinOutput device
        (planner/iterative/rule/TransformExistsApplyToCorrelatedJoin
        .java, plan/SemiJoinNode.java)."""

        def walk(x):
            if isinstance(x, ast.ScalarSubquery):
                if x not in builder.replacements:
                    self._plan_scalar_subquery(builder, x, ctes)
                return
            if isinstance(x, (ast.Exists, ast.InSubquery)):
                self._plan_mark(builder, x, ctes)
                if isinstance(x, ast.InSubquery):
                    walk(x.value)
                return
            if dataclasses.is_dataclass(x):
                for f in dataclasses.fields(x):
                    walk(getattr(x, f.name))
            elif isinstance(x, tuple):
                for i in x:
                    walk(i)

        walk(e)

    def _plan_mark(self, builder: Builder, node, ctes) -> None:
        """EXISTS / IN in a general position -> mark join appending a
        BOOLEAN channel. Uncorrelated IN keeps full three-valued
        semantics ("mark"); EXISTS and correlated IN are two-valued
        ("mark_exists" — for correlated IN that collapses UNKNOWN to
        FALSE, exact in filter contexts where the two coincide)."""
        plain = dataclasses.replace(node, negated=False)
        if plain in builder.replacements:
            return
        ch = len(builder.scope)
        fields = builder.node.fields + (P.Field(None, T.BOOLEAN),)
        if isinstance(node, ast.Exists):
            q = node.query
            if not isinstance(q.body, ast.QuerySpec) or q.body.group_by \
                    or q.with_:
                raise AnalysisError("EXISTS subquery too complex")
            spec = q.body
            inner_items: List[RelationItem] = []
            pool: List[ast.Expression] = []
            self._collect_relations(spec.from_, inner_items, pool, ctes)
            pool.extend(split_conjuncts(spec.where))
            inner, probe_keys, build_keys, residuals = self._decorrelate(
                builder, inner_items, pool, filter_outer=False
            )
            residual_ir = None
            if residuals:
                conv = ExprConverter(
                    Scope.concat(builder.scope, inner.scope)
                )
                residual_ir = ir.and_(
                    *[conv.convert(c) for c in residuals]
                )
            builder.node = P.JoinNode(
                "mark_exists", builder.node, inner.node,
                tuple(probe_keys), tuple(build_keys), residual_ir, fields,
            )
        else:  # InSubquery
            value = node.value
            if not isinstance(value, ast.Identifier):
                raise AnalysisError(
                    "IN (subquery) value must be a column"
                )
            q = node.query
            correlated = self._query_is_correlated(builder, q, ctes)
            if not correlated:
                sub_node, _, _ = self.plan_query(q, ctes)
                if len(sub_node.fields) != 1:
                    raise AnalysisError(
                        "IN subquery must return one column"
                    )
                probe_ch, _ = builder.scope.resolve(value.parts)
                builder.node = P.JoinNode(
                    "mark", builder.node, sub_node,
                    (probe_ch,), (0,), None, fields,
                )
            else:
                # correlated IN: full three-valued semantics from THREE
                # two-valued marks (TransformCorrelatedInPredicateToJoin
                # decomposition): match = EXISTS(corr AND c = x);
                # null-in-set = EXISTS(corr AND c IS NULL);
                # nonempty = EXISTS(corr). IN is then
                # TRUE if match; NULL if null-in-set or (x IS NULL and
                # nonempty); else FALSE.
                if not isinstance(q.body, ast.QuerySpec) or \
                        q.body.group_by or q.with_:
                    raise AnalysisError(
                        "correlated IN subquery too complex"
                    )
                spec = q.body
                if len(spec.select) != 1 or isinstance(
                    spec.select[0].expr, ast.Star
                ):
                    raise AnalysisError(
                        "IN subquery must select one column"
                    )
                sel = spec.select[0].expr

                def add_mark(extra: Optional[ast.Expression],
                             match_value: bool = False) -> int:
                    mark_ch = len(builder.scope)
                    inner_items: List[RelationItem] = []
                    pool: List[ast.Expression] = []
                    self._collect_relations(
                        spec.from_, inner_items, pool, ctes
                    )
                    pool.extend(split_conjuncts(spec.where))
                    if extra is not None:
                        pool.append(extra)
                    inner, pk, bk, residuals = self._decorrelate(
                        builder, inner_items, pool, filter_outer=False
                    )
                    if match_value:
                        # the value = sel correlation passes as EXPLICIT
                        # key channels — injecting the equality into the
                        # pool would let an outer value identifier
                        # mis-resolve against a same-named inner column
                        if (
                            isinstance(sel, ast.Identifier)
                            and inner.scope.try_resolve(sel.parts)
                            is not None
                        ):
                            bk_ch = inner.scope.resolve(sel.parts)[0]
                        else:
                            # expression select item: project it onto a
                            # fresh inner channel and key-join on that
                            sel_ir = ExprConverter(
                                inner.scope
                            ).convert(sel)
                            bk_ch = len(inner.scope.fields)
                            exprs = tuple(
                                ir.InputRef(i, f.type)
                                for i, f in enumerate(inner.node.fields)
                            ) + (sel_ir,)
                            nf = inner.node.fields + (
                                P.Field(None, sel_ir.type),
                            )
                            inner = RelationItem(
                                P.ProjectNode(inner.node, exprs, nf),
                                Scope(
                                    inner.scope.fields
                                    + [ScopeField(
                                        None, None, sel_ir.type
                                    )]
                                ),
                                0.0,
                            )
                        pk = list(pk) + [
                            builder.scope.resolve(value.parts)[0]
                        ]
                        bk = list(bk) + [bk_ch]
                    residual_ir = None
                    if residuals:
                        conv = ExprConverter(
                            Scope.concat(builder.scope, inner.scope)
                        )
                        residual_ir = ir.and_(
                            *[conv.convert(c) for c in residuals]
                        )
                    builder.node = P.JoinNode(
                        "mark_exists", builder.node, inner.node,
                        tuple(pk), tuple(bk), residual_ir,
                        builder.node.fields + (P.Field(None, T.BOOLEAN),),
                    )
                    builder.scope = Scope(
                        builder.scope.fields
                        + [ScopeField(None, None, T.BOOLEAN)]
                    )
                    return mark_ch

                m_match = add_mark(None, match_value=True)
                m_null = add_mark(ast.IsNullPredicate(sel, False))
                m_any = add_mark(None)
                conv = builder.converter()
                v_ir = conv.convert(value)
                b = T.BOOLEAN
                in_ir = ir.Case(
                    (
                        ir.InputRef(m_match, b),
                        ir.or_(
                            ir.InputRef(m_null, b),
                            ir.and_(
                                ir.is_null(v_ir), ir.InputRef(m_any, b)
                            ),
                        ),
                    ),
                    (ir.Literal(True, b), ir.Literal(None, b)),
                    ir.Literal(False, b),
                    b,
                )
                # materialize the three-valued IN as a real channel
                ch = len(builder.scope)
                exprs = tuple(
                    ir.InputRef(i, f.type)
                    for i, f in enumerate(builder.node.fields)
                ) + (in_ir,)
                new_fields = builder.node.fields + (
                    P.Field(None, T.BOOLEAN),
                )
                builder.node = P.ProjectNode(
                    builder.node, exprs, new_fields
                )
                builder.scope = Scope(
                    builder.scope.fields
                    + [ScopeField(None, None, T.BOOLEAN)]
                )
                builder.replacements[plain] = (ch, T.BOOLEAN)
                return
        builder.scope = Scope(
            builder.scope.fields + [ScopeField(None, None, T.BOOLEAN)]
        )
        builder.replacements[plain] = (ch, T.BOOLEAN)

    def _query_is_correlated(self, builder: Builder, q: ast.Query,
                             ctes) -> bool:
        """Does the subquery reference the outer scope? (the
        classification probe shared with _plan_scalar_subquery)."""
        if not isinstance(q.body, ast.QuerySpec) or q.body.from_ is None:
            return False
        probe_items: List[RelationItem] = []
        pool: List[ast.Expression] = []
        self._collect_relations(q.body.from_, probe_items, pool, ctes)
        probe_scope = Scope(
            [f for it in probe_items for f in it.scope.fields]
        )
        for c in pool + split_conjuncts(q.body.where):
            for ident in _idents(c):
                if probe_scope.try_resolve(ident.parts) is None:
                    if builder.scope.try_resolve(ident.parts) is not None:
                        return True
        return False

    def _plan_scalar_subquery(self, builder: Builder, sub: ast.ScalarSubquery, ctes) -> None:
        q = sub.query
        # classify correlation by probing the subquery's FROM scopes
        correlated = False
        if isinstance(q.body, ast.QuerySpec) and q.body.from_ is not None:
            probe_items: List[RelationItem] = []
            pool: List[ast.Expression] = []
            self._collect_relations(q.body.from_, probe_items, pool, ctes)
            probe_scope = Scope([f for it in probe_items for f in it.scope.fields])
            for c in pool + split_conjuncts(q.body.where):
                for ident in _idents(c):
                    if probe_scope.try_resolve(ident.parts) is None:
                        if builder.scope.try_resolve(ident.parts) is not None:
                            correlated = True
        if not correlated:
            node, scope, _ = self.plan_query(q, ctes)
            if len(node.fields) != 1:
                raise AnalysisError("scalar subquery must return one column")
            # cardinality guard: zero rows must yield NULL (not drop the
            # outer rows) and >1 rows must raise — a global aggregate
            # always returns exactly one row, so it skips the guard
            probe = node
            while isinstance(probe, P.ProjectNode):
                probe = probe.child
            always_one = (
                isinstance(probe, P.AggregateNode)
                and not probe.group_channels
            )
            if not always_one:
                node = P.EnforceSingleRowNode(node, node.fields)
            ch = len(builder.scope)
            t = node.fields[0].type
            builder.node = P.JoinNode(
                "cross", builder.node, node, (), (), None,
                builder.node.fields + node.fields,
            )
            builder.scope = Scope(
                builder.scope.fields + [ScopeField(None, None, t)]
            )
            builder.replacements[sub] = (ch, t)
            return
        self._plan_correlated_scalar(builder, q, sub, ctes)

    def _plan_correlated_scalar(self, builder, q: ast.Query, sub, ctes) -> None:
        """Correlated scalar aggregate -> group the subquery by its
        correlation keys and LEFT-join (the TransformCorrelatedScalar-
        AggregationToJoin rule)."""
        if not isinstance(q.body, ast.QuerySpec) or q.body.group_by or q.with_:
            raise AnalysisError("unsupported correlated scalar subquery shape")
        spec = q.body
        if len(spec.select) != 1:
            raise AnalysisError("scalar subquery must select one expression")
        inner_items: List[RelationItem] = []
        pool: List[ast.Expression] = []
        self._collect_relations(spec.from_, inner_items, pool, ctes)
        pool.extend(split_conjuncts(spec.where))
        inner_scope_probe = Scope([f for it in inner_items for f in it.scope.fields])
        inner_filters: List[ast.Expression] = []
        corr_pairs: List[Tuple[ast.Identifier, ast.Identifier]] = []
        for c in pool:
            refs_outer = False
            for ident in _idents(c):
                if inner_scope_probe.try_resolve(ident.parts) is None:
                    if builder.scope.try_resolve(ident.parts) is not None:
                        refs_outer = True
                    else:
                        raise AnalysisError(f"cannot resolve {ident}")
            if not refs_outer:
                inner_filters.append(c)
                continue
            if (
                isinstance(c, ast.BinaryOp)
                and c.op == "eq"
                and isinstance(c.left, ast.Identifier)
                and isinstance(c.right, ast.Identifier)
            ):
                l_inner = inner_scope_probe.try_resolve(c.left.parts)
                r_inner = inner_scope_probe.try_resolve(c.right.parts)
                if l_inner is None and r_inner is not None:
                    corr_pairs.append((c.left, c.right))
                    continue
                if r_inner is None and l_inner is not None:
                    corr_pairs.append((c.right, c.left))
                    continue
            raise AnalysisError(
                "only equality correlation supported in scalar subqueries"
            )
        if not corr_pairs:
            raise AnalysisError("correlated scalar subquery without equi correlation")
        # synthetic query: SELECT <inner keys>..., <value> FROM ... GROUP BY keys
        key_idents = tuple(i for _, i in corr_pairs)
        synth_spec = ast.QuerySpec(
            select=tuple(ast.SelectItem(i) for i in key_idents)
            + (spec.select[0],),
            from_=spec.from_,
            where=conjoin(inner_filters),
            group_by=key_idents,
        )
        node, scope, _ = self.plan_query_spec(synth_spec, (), None, 0, ctes)
        k = len(key_idents)
        value_t = node.fields[k].type
        probe_keys = tuple(builder.scope.resolve(o.parts)[0] for o, _ in corr_pairs)
        ch = len(builder.scope) + k
        builder.node = P.JoinNode(
            "left", builder.node, node, probe_keys, tuple(range(k)), None,
            builder.node.fields + node.fields,
        )
        builder.scope = Scope(
            builder.scope.fields
            + [ScopeField(None, None, f.type) for f in node.fields]
        )
        builder.replacements[sub] = (ch, value_t)

    # ---- aggregation ----
    def _plan_aggregation(self, builder: Builder, group_asts, agg_calls, ctes) -> None:
        conv = builder.converter()
        key_irs = [conv.convert(g) for g in group_asts]
        pre_exprs: List[ir.Expr] = list(key_irs)
        aggs: List[P.AggCall] = []
        prim_cache: Dict[tuple, int] = {}

        def add_prim(kind, arg_ir, out_t, distinct=False) -> int:
            """Append one primitive accumulator, deduplicated
            structurally so composites sharing a moment (e.g. corr and
            covar_pop over the same pair) compute it once."""
            key = (kind, arg_ir, distinct)
            if key in prim_cache:
                return prim_cache[key]
            if arg_ir is None:
                spec = P.AggCall(kind, None, out_t, distinct)
            else:
                arg_ch = len(pre_exprs)
                pre_exprs.append(arg_ir)
                spec = P.AggCall(kind, arg_ch, out_t, distinct)
            aggs.append(spec)
            prim_cache[key] = len(aggs) - 1
            return len(aggs) - 1

        # per original call: ("plain", prim_idx) or ("comp", finisher, out_t)
        # where finisher(ref) builds the result expression from
        # ref(prim_idx) -> InputRef over the AggregateNode's output
        per_call: List[tuple] = []
        for call in agg_calls:
            kind = call.name
            distinct = call.distinct
            if kind == "count" and (
                not call.args or isinstance(call.args[0], ast.Star)
            ):
                per_call.append(
                    ("plain", add_prim("count_star", None, T.BIGINT))
                )
                continue
            if kind in COMPOSITE_AGG_FUNCS:
                per_call.append(
                    self._expand_composite_agg(call, conv, add_prim)
                )
                continue
            if kind == "approx_distinct":
                # exact distinct count through the holistic (gathered)
                # path: mixable with any other aggregates in one SELECT,
                # unlike the old lone-DISTINCT rewrite. The optional
                # max-standard-error argument is accepted and ignored
                # (exact answers satisfy any error bound).
                if len(call.args) not in (1, 2) or distinct:
                    raise AnalysisError(
                        "approx_distinct(x[, e]) takes one or two arguments"
                    )
                x = conv.convert(call.args[0])
                x_ch = len(pre_exprs)
                pre_exprs.append(x)
                aggs.append(P.AggCall("approx_distinct", x_ch, T.BIGINT))
                per_call.append(("plain", len(aggs) - 1))
                continue
            if kind in ("min_by", "max_by"):
                if len(call.args) != 2 or distinct:
                    raise AnalysisError(f"{kind}(x, y) takes two arguments")
                x = conv.convert(call.args[0])
                y = conv.convert(call.args[1])
                x_ch = len(pre_exprs)
                pre_exprs.append(x)
                y_ch = len(pre_exprs)
                pre_exprs.append(y)
                aggs.append(
                    P.AggCall(kind, x_ch, x.type, arg2_channel=y_ch)
                )
                per_call.append(("plain", len(aggs) - 1))
                continue
            if (
                kind in _SKETCH_ACCESSORS
                and call.args
                and isinstance(call.args[0], ast.FunctionCall)
                and call.args[0].name in _SKETCH_AGGS
            ):
                # fused accessor-over-sketch (see _find_agg_calls): the
                # accessor evaluates inside the collect finalizer where
                # the digest is a python string, sidestepping the
                # runtime-dictionary binding wall
                inner = call.args[0]
                if not inner.args:
                    raise AnalysisError(f"{inner.name}() arguments")
                x = conv.convert(inner.args[0])
                if inner.name == "merge":
                    if not x.type.is_string:
                        raise AnalysisError(
                            "merge() takes a serialized sketch"
                        )
                    canon = "sketch_merge"
                elif inner.name in ("tdigest_agg", "qdigest_agg"):
                    if x.type.kind != T.TypeKind.DOUBLE:
                        x = ir.Cast(x, T.DOUBLE)
                    canon = "tdigest_agg"
                else:
                    canon = "approx_set"
                if kind == "cardinality":
                    if canon == "tdigest_agg":
                        raise AnalysisError(
                            "cardinality() reads HyperLogLog sketches"
                        )
                    post, out_t, qv = "card", T.BIGINT, None
                else:
                    if canon == "approx_set":
                        raise AnalysisError(
                            f"{kind}() reads t-digest sketches"
                        )
                    if len(call.args) != 2:
                        raise AnalysisError(f"{kind}(d, q) arguments")
                    q = _const_fold(conv.convert(call.args[1]))
                    if q is None or q.value is None:
                        raise AnalysisError(
                            f"{kind}() argument must be a constant"
                        )
                    if kind == "values_at_quantiles":
                        qv = tuple(float(x) for x in q.value)
                        post = "vaq"
                        out_t = T.array_of(T.DOUBLE)
                    else:
                        # analyzer-level literals carry SQL values (the
                        # physical scaled-int form only exists in the
                        # binder)
                        qv = float(q.value)
                        post = "vq" if kind == "value_at_quantile" else "qv"
                        out_t = T.DOUBLE
                x_ch = len(pre_exprs)
                pre_exprs.append(x)
                aggs.append(P.AggCall(
                    canon, x_ch, out_t, param=qv, post=post
                ))
                per_call.append(("plain", len(aggs) - 1))
                continue
            if kind in ("approx_set", "tdigest_agg", "qdigest_agg",
                        "merge"):
                # sketch builders: HyperLogLog / TDigest serialized on
                # the varchar carrier (expr/pyfns digests; the reference
                # gives these first-class SPI types). approx_set's
                # optional max-error argument is accepted and ignored.
                max_args = {"approx_set": 2, "qdigest_agg": 3}.get(kind, 1)
                if not call.args or len(call.args) > max_args or distinct:
                    raise AnalysisError(f"{kind}() arguments")
                x = conv.convert(call.args[0])
                if kind == "merge":
                    if not x.type.is_string:
                        raise AnalysisError(
                            "merge() takes a serialized sketch"
                        )
                    canon = "sketch_merge"
                elif kind in ("tdigest_agg", "qdigest_agg"):
                    # one mergeable digest carrier serves both SQL
                    # sketch types (lib/trino-qdigest vs TDigest — the
                    # quantile API is identical; accuracy here is
                    # exact-collection grade either way)
                    if x.type.kind != T.TypeKind.DOUBLE:
                        x = ir.Cast(x, T.DOUBLE)
                    canon = "tdigest_agg"
                else:
                    canon = kind
                x_ch = len(pre_exprs)
                pre_exprs.append(x)
                aggs.append(P.AggCall(canon, x_ch, T.VARCHAR))
                per_call.append(("plain", len(aggs) - 1))
                continue
            if kind in ("array_agg", "histogram", "map_union",
                        "bitwise_and_agg", "bitwise_or_agg",
                        "bitwise_xor_agg"):
                if len(call.args) != 1 or distinct:
                    raise AnalysisError(f"{kind}(x) takes one argument")
                x = conv.convert(call.args[0])
                if kind == "array_agg":
                    out_t = T.array_of(x.type)
                elif kind == "histogram":
                    out_t = T.map_of(x.type, T.BIGINT)
                elif kind == "map_union":
                    if not x.type.is_map:
                        raise AnalysisError("map_union() aggregates maps")
                    out_t = x.type
                else:
                    if x.type.is_string or x.type.is_nested or \
                            x.type.kind == T.TypeKind.ARRAY:
                        raise AnalysisError(
                            f"{kind}() aggregates integer values"
                        )
                    out_t = T.BIGINT
                x_ch = len(pre_exprs)
                pre_exprs.append(x)
                aggs.append(P.AggCall(kind, x_ch, out_t))
                per_call.append(("plain", len(aggs) - 1))
                continue
            if kind in ("map_agg", "multimap_agg"):
                if len(call.args) != 2 or distinct:
                    raise AnalysisError(f"{kind}(k, v) takes two arguments")
                k = conv.convert(call.args[0])
                v = conv.convert(call.args[1])
                out_t = (T.map_of(k.type, v.type) if kind == "map_agg"
                         else T.map_of(k.type, T.array_of(v.type)))
                k_ch = len(pre_exprs)
                pre_exprs.append(k)
                v_ch = len(pre_exprs)
                pre_exprs.append(v)
                aggs.append(
                    P.AggCall(kind, k_ch, out_t, arg2_channel=v_ch)
                )
                per_call.append(("plain", len(aggs) - 1))
                continue
            if kind in ("numeric_histogram", "approx_most_frequent"):
                # (buckets, x[, capacity]) — buckets must be constant;
                # the trailing capacity argument is accepted and ignored
                # (the collect path is exact within the gathered rows)
                lo, hi = (2, 3)
                if not lo <= len(call.args) <= hi or distinct:
                    raise AnalysisError(
                        f"{kind}(buckets, x[, capacity]) arguments"
                    )
                b = _const_fold(conv.convert(call.args[0]))
                if b is None or b.value is None:
                    raise AnalysisError(
                        f"{kind}() bucket count must be a constant"
                    )
                if int(b.value) < 1:
                    raise AnalysisError(
                        f"{kind}() bucket count must be positive"
                    )
                x = conv.convert(call.args[1])
                if kind == "numeric_histogram":
                    if x.type.kind != T.TypeKind.DOUBLE:
                        x = ir.Cast(x, T.DOUBLE)
                    out_t = T.map_of(T.DOUBLE, T.DOUBLE)
                else:
                    out_t = T.map_of(x.type, T.BIGINT)
                x_ch = len(pre_exprs)
                pre_exprs.append(x)
                aggs.append(
                    P.AggCall(kind, x_ch, out_t, param=float(b.value))
                )
                per_call.append(("plain", len(aggs) - 1))
                continue
            if kind in ("listagg", "string_agg"):
                if len(call.args) != 2 or distinct:
                    raise AnalysisError(
                        f"{kind}(x, separator) takes two arguments"
                    )
                x = conv.convert(call.args[0])
                if not x.type.is_string:
                    raise AnalysisError(f"{kind}() aggregates VARCHAR values")
                sep = _const_fold(conv.convert(call.args[1]))
                if (
                    sep is None
                    or sep.value is None
                    or not sep.type.is_string
                ):
                    raise AnalysisError(
                        f"{kind}() separator must be a constant string"
                    )
                x_ch = len(pre_exprs)
                pre_exprs.append(x)
                aggs.append(
                    P.AggCall(
                        "listagg", x_ch, T.VARCHAR, separator=str(sep.value)
                    )
                )
                per_call.append(("plain", len(aggs) - 1))
                continue
            if kind == "approx_percentile":
                if len(call.args) != 2 or distinct:
                    raise AnalysisError(
                        "approx_percentile(x, fraction) takes two arguments"
                    )
                x = conv.convert(call.args[0])
                frac = _const_fold(conv.convert(call.args[1]))
                if frac is None or frac.value is None:
                    raise AnalysisError(
                        "approx_percentile() fraction must be a constant"
                    )
                p = float(frac.value)
                if not 0.0 <= p <= 1.0:
                    raise AnalysisError(
                        "approx_percentile() fraction must be in [0, 1]"
                    )
                x_ch = len(pre_exprs)
                pre_exprs.append(x)
                aggs.append(
                    P.AggCall("approx_percentile", x_ch, x.type, percentile=p)
                )
                per_call.append(("plain", len(aggs) - 1))
                continue
            if kind in ("any_value", "arbitrary"):
                kind = "any"
            if len(call.args) != 1:
                raise AnalysisError(f"{call.name}() takes one argument")
            arg = conv.convert(call.args[0])
            out_t = self._agg_out_type(kind, arg.type)
            per_call.append(("plain", add_prim(kind, arg, out_t, distinct)))

        pre_fields = tuple(
            P.Field(
                g.parts[-1] if isinstance(g, ast.Identifier) else None,
                e.type,
            )
            for g, e in zip(group_asts, key_irs)
        ) + tuple(P.Field(None, e.type) for e in pre_exprs[len(key_irs):])
        pre = P.ProjectNode(builder.node, tuple(pre_exprs), pre_fields)

        k = len(key_irs)
        out_fields = tuple(pre_fields[:k]) + tuple(
            P.Field(None, a.out_type) for a in aggs
        )
        builder.node = P.AggregateNode(
            pre, tuple(range(k)), tuple(aggs), out_fields
        )

        def ref(prim_idx: int) -> ir.InputRef:
            return ir.InputRef(k + prim_idx, aggs[prim_idx].out_type)

        # the finisher projection is also needed when dedup collapsed two
        # textually-identical plain aggregates: downstream (grouping
        # sets, select resolution) assumes one output channel per call
        plain_chans = [e[1] for e in per_call if e[0] == "plain"]
        has_comp = (
            any(tag == "comp" for tag, *_ in per_call)
            or len(set(plain_chans)) < len(plain_chans)
        )
        if has_comp:
            # finisher projection over the accumulator outputs (the
            # Accumulator.evaluateFinal step, as a plan-level Project)
            post_exprs: List[ir.Expr] = [
                ir.InputRef(i, e.type) for i, e in enumerate(key_irs)
            ]
            call_types: List[T.DataType] = []
            for entry in per_call:
                if entry[0] == "plain":
                    e: ir.Expr = ref(entry[1])
                else:
                    e = entry[1](ref)
                post_exprs.append(e)
                call_types.append(e.type)
            node_fields = tuple(pre_fields[:k]) + tuple(
                P.Field(None, t) for t in call_types
            )
            builder.node = P.ProjectNode(
                builder.node, tuple(post_exprs), node_fields
            )
            chan_of_call = [k + j for j in range(len(per_call))]
        else:
            call_types = [aggs[e[1]].out_type for e in per_call]
            chan_of_call = [k + e[1] for e in per_call]

        # post-agg scope: group keys keep (qualifier, name) when they were
        # plain identifiers so ORDER BY/SELECT can re-resolve them
        post_fields = []
        replacements: Dict[ast.Expression, Tuple[int, T.DataType]] = {}
        for i, (g, e) in enumerate(zip(group_asts, key_irs)):
            if isinstance(g, ast.Identifier):
                qualifier = g.parts[0] if len(g.parts) == 2 else None
                name = g.parts[-1]
            else:
                qualifier, name = None, None
            post_fields.append(ScopeField(qualifier, name, e.type))
            replacements[g] = (i, e.type)
        n_chan = len(builder.node.fields)
        chan_fields = [None] * (n_chan - k)
        for call, ch, t in zip(agg_calls, chan_of_call, call_types):
            replacements[call] = (ch, t)
            chan_fields[ch - k] = ScopeField(None, None, t)
        for j in range(n_chan - k):
            if chan_fields[j] is None:  # deduped-away duplicate channel
                chan_fields[j] = ScopeField(
                    None, None, builder.node.fields[k + j].type
                )
        builder.scope = Scope(post_fields + chan_fields)
        builder.replacements = replacements

    def _expand_composite_agg(self, call: ast.FunctionCall, conv, add_prim):
        """Lower one composite aggregate to primitive accumulators plus a
        finisher expression (SURVEY.md §2.6 aggregation functions: the
        ~130-function library is built from shared moment/flag
        primitives instead of one compiled accumulator per function)."""
        kind = call.name
        if call.distinct:
            raise AnalysisError(f"DISTINCT {kind}() is not supported")

        def dbl(e: ir.Expr) -> ir.Expr:
            return e if e.type == T.DOUBLE else ir.Cast(e, T.DOUBLE)

        def lit(v) -> ir.Expr:
            return ir.Literal(float(v), T.DOUBLE)

        def mul(a, b):
            return ir.call("mul", T.DOUBLE, a, b)

        def sub(a, b):
            return ir.call("sub", T.DOUBLE, a, b)

        def addx(a, b):
            return ir.call("add", T.DOUBLE, a, b)

        def div(a, b):
            return ir.call("div", T.DOUBLE, a, b)

        def sqrt(a):
            return ir.call("sqrt", T.DOUBLE, a)

        def guard(cond_null: ir.Expr, value: ir.Expr) -> ir.Expr:
            """CASE WHEN cond THEN NULL ELSE value END."""
            return ir.Case(
                (cond_null,), (ir.Literal(None, value.type),), value, value.type
            )

        def nneg(v: ir.Expr) -> ir.Expr:
            """Clamp tiny negative central moments (float error) to 0."""
            return ir.Case(
                (ir.comparison("lt", v, lit(0)),), (lit(0),), v, T.DOUBLE
            )

        if kind in ("count_if", "bool_and", "bool_or", "every"):
            if len(call.args) != 1:
                raise AnalysisError(f"{kind}() takes one argument")
            b = conv.convert(call.args[0])
            if b.type.kind != T.TypeKind.BOOLEAN:
                raise AnalysisError(f"{kind}() argument must be boolean")
            # NULL-preserving 0/1 encoding of the flag
            ib = ir.Case(
                (ir.is_null(b), b),
                (ir.Literal(None, T.BIGINT), ir.Literal(1, T.BIGINT)),
                ir.Literal(0, T.BIGINT),
                T.BIGINT,
            )
            if kind == "count_if":
                i = add_prim("sum", ib, T.BIGINT)
                return (
                    "comp",
                    lambda ref, i=i: ir.Case(
                        (ir.is_null(ref(i)),),
                        (ir.Literal(0, T.BIGINT),),
                        ref(i),
                        T.BIGINT,
                    ),
                    T.BIGINT,
                )
            prim = "min" if kind in ("bool_and", "every") else "max"
            i = add_prim(prim, ib, T.BIGINT)
            return (
                "comp",
                lambda ref, i=i: ir.comparison(
                    "eq", ref(i), ir.Literal(1, T.BIGINT)
                ),
                T.BOOLEAN,
            )

        if kind in (
            "stddev", "stddev_samp", "stddev_pop", "variance", "var_samp",
            "var_pop", "geometric_mean", "skewness", "kurtosis",
        ):
            if len(call.args) != 1:
                raise AnalysisError(f"{kind}() takes one argument")
            x = dbl(conv.convert(call.args[0]))
            n_i = add_prim("count", x, T.BIGINT)
            if kind == "geometric_mean":
                sl_i = add_prim("sum", ir.call("ln", T.DOUBLE, x), T.DOUBLE)

                def fin_geo(ref):
                    n = dbl(ref(n_i))
                    return guard(
                        ir.comparison("eq", ref(n_i), ir.Literal(0, T.BIGINT)),
                        ir.call("exp", T.DOUBLE, div(ref(sl_i), n)),
                    )

                return ("comp", fin_geo, T.DOUBLE)
            s_i = add_prim("sum", x, T.DOUBLE)
            ss_i = add_prim("sum", mul(x, x), T.DOUBLE)
            if kind in ("skewness", "kurtosis"):
                s3_i = add_prim("sum", mul(mul(x, x), x), T.DOUBLE)
                if kind == "kurtosis":
                    s4_i = add_prim("sum", mul(mul(x, x), mul(x, x)), T.DOUBLE)

                def fin_moment(ref, want=kind):
                    n = dbl(ref(n_i))
                    s, ss = ref(s_i), ref(ss_i)
                    mean = div(s, n)
                    m2 = nneg(sub(ss, mul(s, mean)))  # sum((x-mean)^2)
                    # sum((x-mean)^3) from raw moments
                    m3 = addx(
                        sub(ref(s3_i), mul(lit(3), mul(mean, ss))),
                        mul(lit(2), mul(n, mul(mean, mul(mean, mean)))),
                    )
                    if want == "skewness":
                        # sqrt(n) * m3 / m2^1.5, NULL when n < 3 or m2 == 0
                        val = div(
                            mul(sqrt(n), m3), mul(m2, sqrt(m2))
                        )
                        bad = ir.or_(
                            ir.comparison(
                                "lt", ref(n_i), ir.Literal(3, T.BIGINT)
                            ),
                            ir.comparison("le", m2, lit(0)),
                        )
                        return guard(bad, val)
                    # sample excess kurtosis:
                    # n(n+1)(n-1)/((n-2)(n-3)) * m4/m2^2
                    #   - 3(n-1)^2/((n-2)(n-3)),   NULL when n < 4 or m2 == 0
                    m4 = sub(
                        addx(
                            sub(
                                ref(s4_i),
                                mul(lit(4), mul(mean, ref(s3_i))),
                            ),
                            mul(lit(6), mul(mul(mean, mean), ss)),
                        ),
                        mul(
                            lit(3),
                            mul(n, mul(mul(mean, mean), mul(mean, mean))),
                        ),
                    )
                    n1, n2, n3 = sub(n, lit(1)), sub(n, lit(2)), sub(n, lit(3))
                    term1 = mul(
                        div(mul(n, mul(addx(n, lit(1)), n1)), mul(n2, n3)),
                        div(m4, mul(m2, m2)),
                    )
                    term2 = div(mul(lit(3), mul(n1, n1)), mul(n2, n3))
                    bad = ir.or_(
                        ir.comparison("lt", ref(n_i), ir.Literal(4, T.BIGINT)),
                        ir.comparison("le", m2, lit(0)),
                    )
                    return guard(bad, sub(term1, term2))

                return ("comp", fin_moment, T.DOUBLE)

            pop = kind.endswith("_pop")

            def fin_var(ref, pop=pop, want=kind):
                n = dbl(ref(n_i))
                s = ref(s_i)
                m2 = nneg(sub(ref(ss_i), div(mul(s, s), n)))
                denom = n if pop else sub(n, lit(1))
                v = div(m2, denom)
                min_n = 1 if pop else 2
                bad = ir.comparison(
                    "lt", ref(n_i), ir.Literal(min_n, T.BIGINT)
                )
                if want.startswith("stddev"):
                    v = sqrt(v)
                return guard(bad, v)

            return ("comp", fin_var, T.DOUBLE)

        # two-argument covariance family: rows where EITHER argument is
        # NULL are excluded from every moment (pairwise masking)
        if kind == "entropy":
            # -sum(c/S * log2(c/S)) = (ln(S) - sum(c ln c)/S) / ln 2,
            # from two plain sums (the reference's EntropyAggregation
            # keeps the same two-moment state)
            if len(call.args) != 1:
                raise AnalysisError("entropy(c) takes one argument")
            c0 = dbl(conv.convert(call.args[0]))
            bad_in = ir.comparison("lt", c0, lit(0))
            c = ir.Case((bad_in,), (ir.Literal(None, T.DOUBLE),), c0,
                        T.DOUBLE)
            s_i = add_prim("sum", c, T.DOUBLE)
            clnc = mul(c, ir.Case(
                (ir.comparison("le", c, lit(0)),), (lit(0),),
                ir.call("ln", T.DOUBLE, c), T.DOUBLE,
            ))
            slnc_i = add_prim("sum", clnc, T.DOUBLE)

            def fin_entropy(ref):
                s = ref(s_i)
                ent = div(
                    sub(ir.call("ln", T.DOUBLE, s), div(ref(slnc_i), s)),
                    lit(math.log(2.0)),
                )
                zero = ir.or_(
                    ir.is_null(s), ir.comparison("le", s, lit(0))
                )
                return ir.Case((zero,), (lit(0),), ent, T.DOUBLE)

            return ("comp", fin_entropy, T.DOUBLE)
        if kind == "checksum":
            # order-insensitive 64-bit checksum: wrapping sum of per-row
            # value hashes (the reference's ChecksumAggregationFunction
            # sums XxHash64 values; rendered as BIGINT here — the
            # varbinary carrier documents this divergence)
            if len(call.args) != 1:
                raise AnalysisError("checksum(x) takes one argument")
            x = conv.convert(call.args[0])
            if x.type.is_nested or x.type.kind == T.TypeKind.ARRAY:
                raise AnalysisError(
                    "checksum() over nested types is not supported"
                )
            h = ir.Call("checksum_hash", (x,), T.BIGINT)
            i = add_prim("sum", h, T.BIGINT)
            return ("comp", lambda ref, i=i: ref(i), T.BIGINT)
        if kind in ("regr_avgx", "regr_avgy", "regr_count", "regr_r2",
                    "regr_sxx", "regr_sxy", "regr_syy"):
            if len(call.args) != 2:
                raise AnalysisError(f"{kind}(y, x) takes two arguments")
            y0 = dbl(conv.convert(call.args[0]))
            x0 = dbl(conv.convert(call.args[1]))
            both = ir.and_(ir.not_(ir.is_null(y0)), ir.not_(ir.is_null(x0)))

            def masked(ex):
                return ir.Case((both,), (ex,), ir.Literal(None, T.DOUBLE),
                               T.DOUBLE)

            y, x = masked(y0), masked(x0)
            n_i = add_prim("count", y, T.BIGINT)
            if kind == "regr_count":
                return ("comp", lambda ref, i=n_i: ref(i), T.BIGINT)
            sy_i = add_prim("sum", y, T.DOUBLE)
            sx_i = add_prim("sum", x, T.DOUBLE)

            def zero_guard(ref, value):
                return guard(
                    ir.comparison("eq", ref(n_i), ir.Literal(0, T.BIGINT)),
                    value,
                )

            if kind == "regr_avgx":
                return ("comp", lambda ref: zero_guard(
                    ref, div(ref(sx_i), dbl(ref(n_i)))), T.DOUBLE)
            if kind == "regr_avgy":
                return ("comp", lambda ref: zero_guard(
                    ref, div(ref(sy_i), dbl(ref(n_i)))), T.DOUBLE)
            sxy_i = add_prim("sum", mul(y, x), T.DOUBLE)
            sxx_i = add_prim("sum", mul(x, x), T.DOUBLE)
            if kind == "regr_sxy":
                return ("comp", lambda ref: zero_guard(ref, sub(
                    ref(sxy_i),
                    div(mul(ref(sx_i), ref(sy_i)), dbl(ref(n_i))),
                )), T.DOUBLE)
            if kind == "regr_sxx":
                return ("comp", lambda ref: zero_guard(ref, nneg(sub(
                    ref(sxx_i),
                    div(mul(ref(sx_i), ref(sx_i)), dbl(ref(n_i))),
                ))), T.DOUBLE)
            syy_i = add_prim("sum", mul(y, y), T.DOUBLE)
            if kind == "regr_syy":
                return ("comp", lambda ref: zero_guard(ref, nneg(sub(
                    ref(syy_i),
                    div(mul(ref(sy_i), ref(sy_i)), dbl(ref(n_i))),
                ))), T.DOUBLE)

            # regr_r2: square of corr; vx == 0 -> NULL, vy == 0 -> 1
            def fin_r2(ref):
                n = dbl(ref(n_i))
                vx = nneg(
                    sub(ref(sxx_i), div(mul(ref(sx_i), ref(sx_i)), n))
                )
                vy = nneg(
                    sub(ref(syy_i), div(mul(ref(sy_i), ref(sy_i)), n))
                )
                cxy = sub(ref(sxy_i), div(mul(ref(sx_i), ref(sy_i)), n))
                r2 = div(mul(cxy, cxy), mul(vx, vy))
                return ir.Case(
                    (
                        ir.or_(
                            ir.comparison(
                                "eq", ref(n_i), ir.Literal(0, T.BIGINT)
                            ),
                            ir.comparison("le", vx, lit(0)),
                        ),
                        ir.comparison("le", vy, lit(0)),
                    ),
                    (ir.Literal(None, T.DOUBLE), lit(1)),
                    r2,
                    T.DOUBLE,
                )

            return ("comp", fin_r2, T.DOUBLE)
        if kind in ("corr", "covar_pop", "covar_samp", "regr_slope",
                    "regr_intercept"):
            if len(call.args) != 2:
                raise AnalysisError(f"{kind}() takes two arguments")
            y0 = dbl(conv.convert(call.args[0]))
            x0 = dbl(conv.convert(call.args[1]))
            both = ir.and_(ir.not_(ir.is_null(y0)), ir.not_(ir.is_null(x0)))

            def masked(e):
                return ir.Case((both,), (e,), ir.Literal(None, T.DOUBLE),
                               T.DOUBLE)

            y, x = masked(y0), masked(x0)
            n_i = add_prim("count", y, T.BIGINT)
            sy_i = add_prim("sum", y, T.DOUBLE)
            sx_i = add_prim("sum", x, T.DOUBLE)
            sxy_i = add_prim("sum", mul(y, x), T.DOUBLE)
            if kind in ("corr",):
                sxx_i = add_prim("sum", mul(x, x), T.DOUBLE)
                syy_i = add_prim("sum", mul(y, y), T.DOUBLE)

                def fin_corr(ref):
                    n = dbl(ref(n_i))
                    cxy = sub(ref(sxy_i), div(mul(ref(sx_i), ref(sy_i)), n))
                    vx = nneg(
                        sub(ref(sxx_i), div(mul(ref(sx_i), ref(sx_i)), n))
                    )
                    vy = nneg(
                        sub(ref(syy_i), div(mul(ref(sy_i), ref(sy_i)), n))
                    )
                    denom = sqrt(mul(vx, vy))
                    bad = ir.or_(
                        ir.comparison(
                            "eq", ref(n_i), ir.Literal(0, T.BIGINT)
                        ),
                        ir.comparison("le", denom, lit(0)),
                    )
                    return guard(bad, div(cxy, denom))

                return ("comp", fin_corr, T.DOUBLE)
            if kind in ("regr_slope", "regr_intercept"):
                sxx_i = add_prim("sum", mul(x, x), T.DOUBLE)

                def fin_regr(ref, want=kind):
                    n = dbl(ref(n_i))
                    cxy = sub(ref(sxy_i), div(mul(ref(sx_i), ref(sy_i)), n))
                    vx = sub(ref(sxx_i), div(mul(ref(sx_i), ref(sx_i)), n))
                    slope = div(cxy, vx)
                    bad = ir.or_(
                        ir.comparison(
                            "eq", ref(n_i), ir.Literal(0, T.BIGINT)
                        ),
                        ir.comparison("le", nneg(vx), lit(0)),
                    )
                    if want == "regr_slope":
                        return guard(bad, slope)
                    intercept = sub(
                        div(ref(sy_i), n), mul(slope, div(ref(sx_i), n))
                    )
                    return guard(bad, intercept)

                return ("comp", fin_regr, T.DOUBLE)

            pop = kind == "covar_pop"

            def fin_covar(ref, pop=pop):
                n = dbl(ref(n_i))
                cxy = sub(ref(sxy_i), div(mul(ref(sx_i), ref(sy_i)), n))
                denom = n if pop else sub(n, lit(1))
                min_n = 1 if pop else 2
                bad = ir.comparison(
                    "lt", ref(n_i), ir.Literal(min_n, T.BIGINT)
                )
                return guard(bad, div(cxy, denom))

            return ("comp", fin_covar, T.DOUBLE)

        raise AnalysisError(f"unknown aggregate {kind}")

    def _plan_grouping_sets(
        self, builder: Builder, group_asts, sets, agg_calls, ctes
    ) -> None:
        """ROLLUP/CUBE/GROUPING SETS as a UNION ALL of per-set
        aggregations over the same source, each projected onto the
        canonical [all keys..., aggs...] layout with typed NULLs for
        absent keys (the GroupIdNode expansion, unrolled)."""
        base_node, base_scope = builder.node, builder.scope
        base_repl = dict(builder.replacements)
        key_types = [
            ExprConverter(base_scope, base_repl).convert(g).type
            for g in group_asts
        ]
        branches = []
        # larger sets first so the union schema carries real dictionaries
        for s in sorted(sets, key=len, reverse=True):
            b = Builder(base_node, base_scope)
            b.replacements = dict(base_repl)
            self._plan_aggregation(
                b, [group_asts[i] for i in s], agg_calls, ctes
            )
            k_set = len(s)
            exprs: List[ir.Expr] = []
            fields: List[P.Field] = []
            pos_of = {g: p for p, g in enumerate(s)}
            for j, t in enumerate(key_types):
                if j in pos_of:
                    exprs.append(ir.InputRef(pos_of[j], t))
                else:
                    exprs.append(ir.Cast(ir.Literal(None, T.UNKNOWN), t))
                fields.append(P.Field(None, t))
            for i2, call in enumerate(agg_calls):
                t = b.node.fields[k_set + i2].type
                exprs.append(ir.InputRef(k_set + i2, t))
                fields.append(P.Field(None, t))
            branches.append(
                P.ProjectNode(b.node, tuple(exprs), tuple(fields))
            )
        union_fields = branches[0].fields
        builder.node = P.UnionAllNode(tuple(branches), union_fields)
        post_fields = []
        replacements: Dict[ast.Expression, Tuple[int, T.DataType]] = {}
        for j, (g, t) in enumerate(zip(group_asts, key_types)):
            if isinstance(g, ast.Identifier):
                qualifier = g.parts[0] if len(g.parts) == 2 else None
                name = g.parts[-1]
            else:
                qualifier, name = None, None
            post_fields.append(ScopeField(qualifier, name, t))
            replacements[g] = (j, t)
        k = len(group_asts)
        for i2, call in enumerate(agg_calls):
            t = union_fields[k + i2].type
            post_fields.append(ScopeField(None, None, t))
            replacements[call] = (k + i2, t)
        builder.scope = Scope(post_fields)
        builder.replacements = replacements

    def _plan_windows(self, builder: Builder, calls: List[ast.WindowCall]) -> None:
        """Plan WindowNodes: one per distinct (partition, order, frame)
        spec, functions sharing a spec computed together (Trino merges
        window specs the same way in PlanWindowFunctions). Each call's
        result channel is registered as a replacement so SELECT/ORDER BY
        conversion sees a plain channel reference."""
        by_spec: Dict[ast.WindowSpec, List[ast.WindowCall]] = {}
        for c in calls:
            by_spec.setdefault(c.spec, []).append(c)
        for spec, group in by_spec.items():
            conv = builder.converter()
            width = len(builder.scope)
            # pre-projection: identity + partition keys + order keys + args
            pre_exprs: List[ir.Expr] = [
                ir.InputRef(i, f.type) for i, f in enumerate(builder.scope.fields)
            ]

            def channel_of(e: ast.Expression) -> int:
                x = conv.convert(e)
                if isinstance(x, ir.InputRef):
                    return x.index
                pre_exprs.append(x)
                return len(pre_exprs) - 1

            part_channels = tuple(channel_of(e) for e in spec.partition_by)
            order_keys = []
            for s in spec.order_by:
                ch = channel_of(s.expr)
                nf = s.nulls_first if s.nulls_first is not None else s.descending
                order_keys.append(SortKey(ch, s.descending, nf))
            functions: List[P.WindowFuncSpec] = []
            for c in group:
                functions.append(self._window_func(c, channel_of, conv))
            pre_fields = tuple(
                P.Field(None, e.type) for e in pre_exprs
            )
            pre = P.ProjectNode(builder.node, tuple(pre_exprs), pre_fields)
            out_fields = pre_fields + tuple(
                P.Field(None, f.out_type) for f in functions
            )
            builder.node = P.WindowNode(
                pre, part_channels, tuple(order_keys), tuple(functions),
                spec.frame, out_fields,
            )
            new_fields = list(builder.scope.fields)
            for e in pre_exprs[width:]:
                new_fields.append(ScopeField(None, None, e.type))
            for i, (c, f) in enumerate(zip(group, functions)):
                new_fields.append(ScopeField(None, None, f.out_type))
                builder.replacements[c] = (len(pre_exprs) + i, f.out_type)
            builder.scope = Scope(new_fields)

    def _window_func(self, c: ast.WindowCall, channel_of, conv) -> P.WindowFuncSpec:
        name = c.name
        if name in ("row_number", "rank", "dense_rank"):
            if c.args:
                raise AnalysisError(f"{name}() takes no arguments")
            return P.WindowFuncSpec(name, None, T.BIGINT)
        if name in ("percent_rank", "cume_dist"):
            if c.args:
                raise AnalysisError(f"{name}() takes no arguments")
            return P.WindowFuncSpec(name, None, T.DOUBLE)
        if name == "ntile":
            n = c.args[0] if c.args else None
            if not isinstance(n, ast.NumberLiteral) or not n.text.isdigit():
                raise AnalysisError("ntile() requires a literal integer")
            return P.WindowFuncSpec("ntile", None, T.BIGINT, offset=int(n.text))
        if name in ("lead", "lag"):
            if not c.args:
                raise AnalysisError(f"{name}() requires an argument")
            ch = channel_of(c.args[0])
            off = 1
            if len(c.args) > 1:
                a1 = c.args[1]
                if not isinstance(a1, ast.NumberLiteral) or not a1.text.isdigit():
                    raise AnalysisError(f"{name}() offset must be a literal integer")
                off = int(a1.text)
            if len(c.args) > 2:
                raise AnalysisError(f"{name}() default values not supported")
            t = conv.convert(c.args[0]).type
            return P.WindowFuncSpec(name, ch, t, offset=off)
        if name in ("first_value", "last_value"):
            ch = channel_of(c.args[0])
            t = conv.convert(c.args[0]).type
            return P.WindowFuncSpec(name, ch, t)
        if name == "nth_value":
            if len(c.args) != 2:
                raise AnalysisError("nth_value(x, n) takes two arguments")
            a1 = c.args[1]
            if not isinstance(a1, ast.NumberLiteral) or not a1.text.isdigit():
                raise AnalysisError(
                    "nth_value() offset must be a literal positive integer"
                )
            n = int(a1.text)
            if n < 1:
                raise AnalysisError("nth_value() offset must be >= 1")
            ch = channel_of(c.args[0])
            t = conv.convert(c.args[0]).type
            return P.WindowFuncSpec(name, ch, t, offset=n)
        if name == "count":
            if not c.args or isinstance(c.args[0], ast.Star):
                return P.WindowFuncSpec("count_star", None, T.BIGINT)
            return P.WindowFuncSpec("count", channel_of(c.args[0]), T.BIGINT)
        if name in ("sum", "avg", "min", "max"):
            ch = channel_of(c.args[0])
            t = conv.convert(c.args[0]).type
            return P.WindowFuncSpec(name, ch, self._agg_out_type(name, t))
        raise AnalysisError(f"unknown window function {name}()")

    @staticmethod
    def _agg_out_type(kind: str, arg_t: T.DataType) -> T.DataType:
        if kind == "count":
            return T.BIGINT
        if kind == "avg":
            # Trino: avg(decimal(p, s)) -> decimal(p, s)
            # (DecimalAverageAggregation @OutputFunction("decimal(p,s)"))
            if arg_t.is_decimal:
                return T.decimal(arg_t.precision or 18, arg_t.scale or 0)
            return T.DOUBLE
        if kind == "sum":
            # Trino: sum(decimal(p, s)) -> decimal(38, s)
            # (DecimalSumAggregation @OutputFunction("decimal(38,s)"))
            if arg_t.is_decimal:
                return T.decimal(T.MAX_DECIMAL_PRECISION, arg_t.scale or 0)
            if arg_t.is_floating:
                return T.DOUBLE
            return T.BIGINT
        if kind in ("min", "max", "any"):
            return arg_t
        raise AnalysisError(f"unknown aggregate {kind}")

    # ---- select helpers ----
    def _expand_stars(self, spec: ast.QuerySpec, scope: Scope) -> List[ast.SelectItem]:
        out: List[ast.SelectItem] = []
        for item in spec.select:
            if isinstance(item.expr, ast.Star):
                q = item.expr.qualifier
                for f in scope.fields:
                    if f.name is None:
                        continue
                    if q is not None and f.qualifier != q:
                        continue
                    parts = (f.qualifier, f.name) if f.qualifier else (f.name,)
                    out.append(ast.SelectItem(ast.Identifier(parts)))
            else:
                out.append(item)
        return out

    @staticmethod
    def _resolve_group_ordinals(group_by, select_exprs) -> List[ast.Expression]:
        out = []
        for g in group_by:
            if isinstance(g, ast.NumberLiteral) and g.text.isdigit():
                idx = int(g.text) - 1
                if not 0 <= idx < len(select_exprs):
                    raise AnalysisError(f"GROUP BY ordinal {g.text} out of range")
                out.append(select_exprs[idx])
            else:
                out.append(g)
        return out

    @staticmethod
    def _output_name(item: ast.SelectItem, i: int) -> str:
        if item.alias:
            return item.alias
        if isinstance(item.expr, ast.Identifier):
            return item.expr.parts[-1]
        return f"_col{i}"

    @staticmethod
    def _order_by_channel(e, select_items, select_exprs, names) -> Optional[int]:
        if isinstance(e, ast.NumberLiteral) and e.text.isdigit():
            idx = int(e.text) - 1
            if not 0 <= idx < len(select_exprs):
                raise AnalysisError(f"ORDER BY ordinal {e.text} out of range")
            return idx
        if isinstance(e, ast.Identifier) and len(e.parts) == 1:
            if e.parts[0] in names:
                return names.index(e.parts[0])
        if e in select_exprs:
            return select_exprs.index(e)
        return None


def _pattern_var_names(node) -> Set[str]:
    """Variable names (lowercased) appearing in a pattern tuple-AST."""
    kind = node[0]
    if kind == "var":
        return {node[1].lower()}
    if kind in ("seq", "alt"):
        out: Set[str] = set()
        for p in node[1]:
            out |= _pattern_var_names(p)
        return out
    return _pattern_var_names(node[1])


def _validate_array_usage(node: P.PlanNode) -> None:
    """Nested columns (ARRAY/MAP/ROW) have no value-wise ordering/hash
    operators (the physical per-row value is the LENGTH for array/map
    and a constant presence byte for row — block.py), so using them as
    grouping/sort/join/partition keys would silently collapse distinct
    values. Reject at analysis time (the reference's ArrayType/MapType/
    RowType have real operators; until this engine's do, fail loudly)."""

    def bad(where: str):
        raise AnalysisError(
            f"ARRAY/MAP/ROW values cannot be used as {where} (use UNNEST,"
            " subscripts or cardinality to operate on nested contents)"
        )

    def check(child: P.PlanNode, channels, where: str):
        for ch in channels:
            if child.fields[ch].type.is_nested:
                bad(where)

    if isinstance(node, P.AggregateNode):
        check(node.child, node.group_channels, "grouping keys")
        for a in node.aggs:
            if a.kind in ("map_union", "array_agg"):
                # collect-path aggregates consume the nested VALUE
                # host-side (no value-wise device operator needed)
                continue
            for ch in (a.arg_channel, a.arg2_channel):
                if ch is not None and node.child.fields[ch].type.is_nested:
                    bad("aggregate arguments")
    elif isinstance(node, P.JoinNode):
        check(node.left, node.left_keys, "join keys")
        check(node.right, node.right_keys, "join keys")
    elif isinstance(node, (P.SortNode, P.TopNNode)):
        check(node.child, [k.channel for k in node.keys], "sort keys")
    elif isinstance(node, P.WindowNode):
        check(node.child, node.partition_channels, "window partition keys")
        check(node.child, [k.channel for k in node.order_keys],
              "window order keys")
    elif isinstance(node, P.MatchRecognizeNode):
        check(node.child, node.partition_channels, "pattern partition keys")
    for c in node.children():
        _validate_array_usage(c)
