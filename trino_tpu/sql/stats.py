"""Statistics propagation + cost-based decisions.

Analogue of main/cost/ (StatsCalculator rule set: FilterStatsCalculator,
JoinStatsRule, AggregationStatsRule; CostCalculatorUsingExchanges —
SURVEY.md §2.2) reduced to the estimates the planner consults: row
counts and per-channel (ndv, null_fraction, low, high). Consumers:
broadcast-vs-partitioned join choice and adaptive partition counts
(DeterminePartitionCount.java:90), plus EXPLAIN row estimates."""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

from trino_tpu.expr import ir
from trino_tpu.sql import plan as P

UNKNOWN_FILTER_COEFFICIENT = 0.33  # fallback selectivity


@dataclasses.dataclass
class ColStats:
    ndv: Optional[float] = None
    null_fraction: Optional[float] = None
    low: Optional[float] = None
    high: Optional[float] = None


@dataclasses.dataclass
class PlanStats:
    row_count: float
    columns: Dict[int, ColStats] = dataclasses.field(default_factory=dict)

    def col(self, ch: int) -> ColStats:
        return self.columns.get(ch, ColStats())


class StatsCalculator:
    def __init__(self, catalogs):
        self._catalogs = catalogs
        # id(node) -> (node, stats); the node reference keeps the id alive
        self._memo: Dict[int, tuple] = {}

    def stats(self, node: P.PlanNode) -> PlanStats:
        # memo holds the node itself: id() alone would collide once a
        # previously-estimated node is garbage collected
        key = id(node)
        hit = self._memo.get(key)
        if hit is not None and hit[0] is node:
            return hit[1]
        # adaptive execution substitutes materialized subtrees back into
        # the plan carrying their EXACT observed statistics — those beat
        # any estimate this calculator could derive
        ps = getattr(node, "plan_stats", None)
        if isinstance(ps, PlanStats):
            self._memo[key] = (node, ps)
            return ps
        m = getattr(self, f"_{type(node).__name__}", None)
        if m is None and isinstance(node, P.ValuesNode):
            m = self._ValuesNode
        out = m(node) if m is not None else self._default(node)
        self._memo[key] = (node, out)
        return out

    def _default(self, node: P.PlanNode) -> PlanStats:
        kids = node.children()
        if not kids:
            return PlanStats(1e6)
        return self.stats(kids[0])

    # -- leaves --
    def _ScanNode(self, node: P.ScanNode) -> PlanStats:
        try:
            ts = self._catalogs.get(node.catalog).metadata.get_table_statistics(
                node.handle
            )
        except Exception:
            return PlanStats(1e9)
        rows = float(ts.row_count) if ts.row_count is not None else 1e9
        cols: Dict[int, ColStats] = {}
        for i, name in enumerate(node.columns):
            t = ts.columns.get(name)
            if t is not None:
                ndv, nf, lo, hi = t
                cols[i] = ColStats(
                    ndv,
                    nf,
                    _as_float(lo),
                    _as_float(hi),
                )
        return PlanStats(rows, cols)

    def _ValuesNode(self, node: P.ValuesNode) -> PlanStats:
        return PlanStats(float(len(node.rows)))

    # -- relational --
    def _FilterNode(self, node: P.FilterNode) -> PlanStats:
        child = self.stats(node.child)
        sel = _selectivity(node.predicate, child)
        rows = max(child.row_count * sel, 1.0)
        cols = {
            ch: dataclasses.replace(
                cs, ndv=min(cs.ndv, rows) if cs.ndv is not None else None
            )
            for ch, cs in child.columns.items()
        }
        return PlanStats(rows, cols)

    def _ProjectNode(self, node: P.ProjectNode) -> PlanStats:
        child = self.stats(node.child)
        cols: Dict[int, ColStats] = {}
        for i, e in enumerate(node.exprs):
            if isinstance(e, ir.InputRef):
                cs = child.columns.get(e.index)
                if cs is not None:
                    cols[i] = cs
        return PlanStats(child.row_count, cols)

    def _AggregateNode(self, node: P.AggregateNode) -> PlanStats:
        child = self.stats(node.child)
        if not node.group_channels:
            return PlanStats(1.0)
        ndv_prod = 1.0
        for c in node.group_channels:
            ndv = child.col(c).ndv
            ndv_prod *= ndv if ndv is not None else math.sqrt(child.row_count)
        rows = max(min(child.row_count, ndv_prod), 1.0)
        cols = {
            i: child.col(c) for i, c in enumerate(node.group_channels)
        }
        return PlanStats(rows, cols)

    def _JoinNode(self, node: P.JoinNode) -> PlanStats:
        left = self.stats(node.left)
        right = self.stats(node.right)
        if node.kind == "cross":
            return PlanStats(left.row_count * right.row_count, dict(left.columns))
        if node.kind in ("semi", "anti"):
            return PlanStats(
                max(left.row_count * 0.5, 1.0), dict(left.columns)
            )
        if node.kind in ("mark", "mark_exists"):
            # mark joins preserve probe cardinality exactly; the output
            # is the probe columns + one BOOLEAN channel (no stats)
            return PlanStats(left.row_count, dict(left.columns))
        # equi-join estimate: |L|*|R| / max(ndv of the key pair).
        # Unknown NDV defaults to the side's ROW COUNT (join keys are
        # near-unique on one side in analytic schemas — FK->PK). The old
        # sqrt(rows) default overestimated join output ~25x on TPC-H Q3
        # through the memory connector, which flipped the reorderer into
        # building the lookup on the 6M-row side.
        denom = 1.0
        for lk, rk in zip(node.left_keys, node.right_keys):
            ndv_l = left.col(lk).ndv
            ndv_r = right.col(rk).ndv
            key_ndv = max(
                ndv_l if ndv_l is not None else left.row_count,
                ndv_r if ndv_r is not None else right.row_count,
            )
            denom *= max(key_ndv, 1.0)
        rows = max(left.row_count * right.row_count / denom, 1.0)
        if node.kind == "left":
            rows = max(rows, left.row_count)
        cols = dict(left.columns)
        width_l = len(node.left.fields)
        for ch, cs in right.columns.items():
            cols[width_l + ch] = cs
        return PlanStats(rows, cols)

    def _WindowNode(self, node: P.WindowNode) -> PlanStats:
        return self.stats(node.child)

    def _SortNode(self, node: P.SortNode) -> PlanStats:
        return self.stats(node.child)

    def _TopNNode(self, node: P.TopNNode) -> PlanStats:
        child = self.stats(node.child)
        return PlanStats(min(child.row_count, float(node.count)), dict(child.columns))

    def _LimitNode(self, node: P.LimitNode) -> PlanStats:
        child = self.stats(node.child)
        if node.count is None:
            return child
        return PlanStats(
            min(child.row_count, float(node.count)), dict(child.columns)
        )

    def _UnionAllNode(self, node: P.UnionAllNode) -> PlanStats:
        return PlanStats(sum(self.stats(c).row_count for c in node.inputs))

    def _OutputNode(self, node: P.OutputNode) -> PlanStats:
        return self.stats(node.child)

    def _ExchangeNode(self, node: P.ExchangeNode) -> PlanStats:
        return self.stats(node.child)

    def _RemoteSourceNode(self, node: P.RemoteSourceNode) -> PlanStats:
        return PlanStats(1e6)


def _as_float(v) -> Optional[float]:
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def _selectivity(e: ir.Expr, child: PlanStats) -> float:
    """FilterStatsCalculator-style predicate selectivity."""
    if isinstance(e, ir.Call):
        if e.name == "and":
            return _selectivity(e.args[0], child) * _selectivity(e.args[1], child)
        if e.name == "or":
            a = _selectivity(e.args[0], child)
            b = _selectivity(e.args[1], child)
            return min(a + b, 1.0)
        if e.name == "not":
            return max(1.0 - _selectivity(e.args[0], child), 0.05)
        if e.name in ("eq", "ne", "lt", "le", "gt", "ge") and len(e.args) == 2:
            col, lit = e.args
            op = e.name
            if isinstance(lit, ir.InputRef) and isinstance(col, ir.Literal):
                # normalizing `lit OP col` to `col OP' lit` flips the
                # comparison direction
                col, lit = lit, col
                op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}.get(op, op)
            if isinstance(col, ir.InputRef) and isinstance(lit, ir.Literal):
                cs = child.col(col.index)
                if op == "eq":
                    return 1.0 / cs.ndv if cs.ndv else 0.1
                if op == "ne":
                    return 1.0 - (1.0 / cs.ndv if cs.ndv else 0.1)
                lo, hi = cs.low, cs.high
                v = _as_float(lit.value)
                if lo is not None and hi is not None and v is not None and hi > lo:
                    frac = (v - lo) / (hi - lo)
                    frac = min(max(frac, 0.0), 1.0)
                    return frac if op in ("lt", "le") else 1.0 - frac
                return UNKNOWN_FILTER_COEFFICIENT
    return UNKNOWN_FILTER_COEFFICIENT


def determine_partition_count(
    rows: float, max_partitions: int, rows_per_partition: float = 1e6
) -> int:
    """Adaptive stage parallelism from stats
    (DeterminePartitionCount.java:90)."""
    want = math.ceil(rows / rows_per_partition)
    return max(1, min(max_partitions, want))
