"""Physical planner: logical plan -> reusable operator-factory pipelines.

Analogue of Trino's LocalExecutionPlanner + DriverFactory (main/sql/
planner/LocalExecutionPlanner.java:520 — the operator-selection
switchboard, visitTableScan:2124 / visitAggregation:1926 /
visitJoin:2487; operators are created per-driver from factories,
SqlTaskExecution.java:100). Expression binding and jit compilation
happen ONCE at plan time (the ExpressionCompiler/PageFunctionCompiler
cache discipline, §2.9); each execution instantiates fresh operator
state from the factories, sharing the compiled device programs — so
re-running a cached query never re-traces.

A factory is `ctx -> Operator`; `ctx` is the per-execution context that
materializes join bridges/buffers so concurrent executions never share
mutable state.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from trino_tpu import types as T
from trino_tpu.block import Dictionary, RelBatch
from trino_tpu.connectors.spi import CatalogManager
from trino_tpu.exec import (
    AggSpec,
    BufferSink,
    BufferSource,
    CrossJoinBuildSink,
    CrossJoinOperator,
    FilterProjectOperator,
    HashAggregationOperator,
    HashBuildSink,
    JoinBridge,
    LimitOperator,
    LookupJoinOperator,
    MxuJoinAggOperator,
    Operator,
    Pipeline,
    SortOperator,
    TableScanOperator,
    TopNOperator,
    ValuesOperator,
)
from trino_tpu.exec.operators import make_filter_project_fn, make_residual_fn
from trino_tpu.expr.compile import Bound, ExprBinder
from trino_tpu.expr.ir import Expr, InputRef
from trino_tpu.sql import plan as P

Schema = List[Tuple[T.DataType, Optional[Dictionary]]]
Factory = Callable[[dict], Operator]


def _mem_ctx(ctx: dict):
    """Per-operator MemoryContext when the execution context carries a
    pool (OperatorContext.newLocalUserMemoryContext analogue). Contexts
    carry the query id (the pool's per-query kill ledger) and register
    in ctx["memory_contexts"] so task teardown can close them — on a
    SHARED worker pool a failed task must not leak its reservation."""
    pool = ctx.get("memory_pool")
    if pool is None:
        return None
    from trino_tpu.runtime.memory import MemoryContext

    mc = MemoryContext(pool, query_id=ctx.get("query_id"))
    ctx.setdefault("memory_contexts", []).append(mc)
    return mc


class PhysicalPlan:
    """Cached executable form of one query: factory pipelines + the main
    chain; instantiate() stamps a fresh operator DAG."""

    def __init__(
        self,
        pipelines: List[List[Factory]],
        chain: List[Factory],
        schema: Schema,
        warmup_entries: Sequence = (),
    ):
        self.pipeline_factories = pipelines
        self.chain_factories = chain
        self.schema = schema
        # compile.warmup.WarmupEntry list: the fused filter/project
        # programs this plan will dispatch, with their census-predicted
        # capacity classes (the AOT warmup input)
        self.warmup_entries = list(warmup_entries)

    def instantiate(
        self, ctx: Optional[dict] = None
    ) -> Tuple[List[Pipeline], List[Operator]]:
        """`ctx` seeds the per-execution context; the task runtime
        injects "make_remote_source" for RemoteSourceNode leaves."""
        ctx = {} if ctx is None else ctx
        pipelines = [
            Pipeline([f(ctx) for f in fs]) for fs in self.pipeline_factories
        ]
        chain = [f(ctx) for f in self.chain_factories]
        return pipelines, chain


class _AggWarmer:
    """WarmupEntry.fn adapter for aggregation kernels. The group-reduce
    programs (ops/groupby) are module-level jits keyed by shape and
    static config, so driving a throwaway operator instance over the
    dead batch seeds the very dispatch cache the real query hits."""

    def __init__(self, groups, specs, schema, step):
        self.groups = list(groups)
        self.specs = list(specs)
        self.schema = list(schema)
        self.step = step

    def __call__(self, batch):
        op = HashAggregationOperator(
            self.groups, self.specs, self.schema, step=self.step
        )
        op.add_input(batch)
        op.finish()
        for _ in range(8):
            if op.get_output() is None:
                break


class _JoinWarmer:
    """Dead-batch join warmup: build an empty lookup source at the
    build side's predicted capacity, then probe it at the entry's
    capacity — the (probe_cap, build_cap) pair the real query
    dispatches."""

    def __init__(self, lkeys, rkeys, kind, probe_schema, build_schema,
                 build_cap):
        self.lkeys, self.rkeys, self.kind = list(lkeys), list(rkeys), kind
        self.probe_schema = list(probe_schema)
        self.build_schema = list(build_schema)
        self.build_cap = int(build_cap)

    def __call__(self, batch):
        from trino_tpu.compile.warmup import zeros_batch

        bridge = JoinBridge()
        sink = HashBuildSink(bridge, self.rkeys, self.build_schema)
        sink.add_input(zeros_batch(self.build_schema, self.build_cap))
        sink.finish()
        op = LookupJoinOperator(
            bridge, self.lkeys, self.kind, self.probe_schema
        )
        op.add_input(batch)
        op.finish()
        for _ in range(8):
            if op.get_output() is None:
                break


class LocalPlanner:
    def __init__(
        self,
        catalogs: CatalogManager,
        batch_rows: int = 1 << 20,
        target_splits: int = 1,
        remote_schemas: Optional[Dict[int, "Schema"]] = None,
        scan_slice: Optional[Tuple[int, int]] = None,
        dynamic_filtering: bool = True,
        stabilizer=None,
        mxu_join: bool = False,
        mxu_join_min_work: float = 16.0,
    ):
        """`remote_schemas` maps producer fragment id -> output Schema
        (with dictionaries) for RemoteSourceNode leaves; `scan_slice`
        (task_index, task_count) restricts scans to this task's share of
        the connector splits (the SourcePartitionedScheduler assignment,
        collapsed to deterministic round-robin). `stabilizer`
        (compile.shapes.ShapeStabilizer) pads scan chunks onto the
        session's capacity ladder and enables warmup-entry collection."""
        self.catalogs = catalogs
        self.batch_rows = batch_rows
        self.target_splits = target_splits
        self.remote_schemas = remote_schemas or {}
        self.scan_slice = scan_slice
        self.dynamic_filtering = dynamic_filtering
        self.stabilizer = stabilizer
        self.mxu_join = mxu_join
        self.mxu_join_min_work = float(mxu_join_min_work)
        self.pipelines: List[List[Factory]] = []
        self._next_key = 0
        self._warmup_entries: List = []
        self._stats_calc = None

    # -- public --
    def plan(self, root: P.PlanNode) -> PhysicalPlan:
        chain, schema = self._visit(root)
        return PhysicalPlan(
            self.pipelines, chain, schema,
            warmup_entries=self._warmup_entries,
        )

    # -- helpers --
    def _key(self) -> int:
        self._next_key += 1
        return self._next_key

    def _bind(self, e: Expr, schema: Schema) -> Bound:
        return ExprBinder([t for t, _ in schema], [d for _, d in schema]).bind(e)

    def _identity(self, schema: Schema) -> List[Bound]:
        return [
            self._bind(InputRef(i, t), schema) for i, (t, _) in enumerate(schema)
        ]

    # -- dispatch --
    def _visit(self, node: P.PlanNode) -> Tuple[List[Factory], Schema]:
        m = getattr(self, f"_visit_{type(node).__name__}", None)
        if m is None:
            raise NotImplementedError(f"no physical plan for {type(node).__name__}")
        return m(node)

    def _visit_OutputNode(self, node: P.OutputNode):
        return self._visit(node.child)

    def _visit_ScanNode(self, node: P.ScanNode):
        conn = self.catalogs.get(node.catalog)
        splits = conn.split_manager.get_splits(node.handle, self.target_splits)
        if self.scan_slice is not None:
            idx, count = self.scan_slice
            splits = splits[idx::count]
        columns = list(node.columns)
        page_source = conn.page_source
        batch_rows = self.batch_rows
        stabilizer = self.stabilizer
        schema: Schema = [
            (f.type, conn.metadata.column_dictionary(node.handle, c))
            for c, f in zip(node.columns, node.fields)
        ]

        def factory(ctx):
            return TableScanOperator(
                page_source, splits, columns, batch_rows, stabilizer=stabilizer
            )

        # predicted output capacity classes (main + tail) — consumed by
        # _append_fp to build warmup entries for downstream fused stages
        factory.out_caps = self._scan_caps(node)
        return [factory], schema

    def _scan_caps(self, node: P.PlanNode) -> Optional[Tuple[int, ...]]:
        """Census-predicted capacity classes of a scan's output batches,
        None when stabilization is off or stats are unusable."""
        if self.stabilizer is None:
            return None
        try:
            if self._stats_calc is None:
                from trino_tpu.sql.stats import StatsCalculator

                self._stats_calc = StatsCalculator(self.catalogs)
            rows = self._stats_calc.stats(node).row_count
        except Exception:
            return None
        if not rows or rows != rows or rows >= 1e9:  # missing-stats fallback
            return None
        return self.stabilizer.scan_classes(rows)

    def _visit_ValuesNode(self, node: P.ValuesNode):
        keys = [f.name or f"_c{i}" for i, f in enumerate(node.fields)]
        if len(set(keys)) != len(keys):
            # spooled join subtrees repeat column names (k, name, k,
            # name); a name-keyed dict would silently drop channels
            keys = [f"{k}_{i}" for i, k in enumerate(keys)]
        data = {k: [] for k in keys}
        for row in node.rows:
            for k, v in zip(keys, row):
                data[k].append(v)
        schema_t = [(k, f.type) for k, f in zip(keys, node.fields)]
        batch = RelBatch.from_pydict(schema_t, data)
        schema: Schema = [(c.type, c.dictionary) for c in batch.columns]

        def factory(ctx):
            return ValuesOperator([batch])

        if self.stabilizer is not None and batch.columns:
            factory.out_caps = (batch.capacity,)
        return [factory], schema

    # adaptive execution: a materialized subtree IS a values source;
    # its batch pads to bucket_capacity like any other, so re-planned
    # programs land on existing capacity-ladder shape classes
    _visit_SpooledValuesNode = _visit_ValuesNode

    # -- fusion helpers (program-count reduction; see compose_batch_fns) --
    def _cached_fp(self, flt: Optional[Bound], bounds: List[Bound],
                   schema: Schema, fingerprint) -> object:
        """Build (or reuse from the process-wide ProgramCache) the fused
        filter/project jit for a structurally-identified stage. Cache
        keys combine the expr-IR fingerprint with the input schema
        signature (dictionary values included); anything uncacheable —
        runtime dictionaries, non-structural reprs — builds a private
        jit exactly as before."""
        from trino_tpu.compile.cache import (
            PROGRAM_CACHE,
            expr_fingerprint,
            schema_cache_key,
        )

        fp = expr_fingerprint(fingerprint) if fingerprint is not None else None
        skey = schema_cache_key(schema)
        if fp is None or skey is None:
            return make_filter_project_fn(flt, bounds, name="FilterProjectOperator")
        return PROGRAM_CACHE.get_or_create(
            ("fp", fp, skey),
            lambda: make_filter_project_fn(
                flt, bounds, name="FilterProjectOperator"
            ),
        )

    def _append_fp(self, chain: List[Factory], fn,
                   in_schema: Optional[Schema],
                   out_schema: Optional[Schema]) -> None:
        """Append a filter/project stage, folding it into a directly
        preceding one so adjacent stages share a device program. Also
        records the stage's warmup entry: the (possibly composed) jit,
        the schema feeding it, and the capacity classes predicted for
        the chain's source."""
        from trino_tpu.compile.cache import PROGRAM_CACHE
        from trino_tpu.exec.operators import compose_batch_fns

        prev = chain[-1] if chain else None
        pf = getattr(prev, "fused_fn", None)
        caps = getattr(prev, "out_caps", None)
        if pf is not None:
            chain.pop()
            prev_entry = getattr(prev, "warmup_entry", None)
            if prev_entry is not None:
                # the folded stage dispatches as one program; its parts
                # must not be warmed separately
                self._warmup_entries.remove(prev_entry)
                in_schema = prev_entry.in_schema
            inner = fn
            k1, k2 = PROGRAM_CACHE.key_of(pf), PROGRAM_CACHE.key_of(inner)
            if k1 is not None and k2 is not None:
                fn = PROGRAM_CACHE.get_or_create(
                    ("compose", k1, k2),
                    lambda: compose_batch_fns(
                        pf, inner, name="FilterProjectOperator"
                    ),
                )
            else:
                fn = compose_batch_fns(pf, inner, name="FilterProjectOperator")

        def factory(ctx, fn=fn):
            return FilterProjectOperator(None, (), fn=fn)

        factory.fused_fn = fn
        # filter/project preserves capacity, so the source classes flow
        # through for any further folding above this stage
        factory.out_caps = caps
        if caps and in_schema is not None and out_schema is not None:
            from trino_tpu.compile.warmup import WarmupEntry

            entry = WarmupEntry(
                operator="FilterProjectOperator",
                fn=fn,
                in_schema=list(in_schema),
                out_dtypes=tuple(str(t) for t, _ in out_schema),
                capacities=tuple(caps),
            )
            factory.warmup_entry = entry
            self._warmup_entries.append(entry)
        chain.append(factory)

    def _record_kernel_warmup(self, operator: str, warmer, in_schema,
                              out_schema, caps) -> None:
        """Warmup entry for a blocking kernel (aggregation / join):
        the census predicted `caps` input classes; the warmer drives a
        throwaway operator so the shared kernel jits compile ahead of
        first touch. No-op when the census has no prediction."""
        if not caps:
            return
        from trino_tpu.compile.warmup import WarmupEntry

        self._warmup_entries.append(WarmupEntry(
            operator=operator,
            fn=warmer,
            in_schema=list(in_schema),
            out_dtypes=tuple(str(t) for t, _ in out_schema),
            capacities=tuple(caps),
        ))

    @staticmethod
    def _take_fused(chain: List[Factory]):
        """Pop a trailing fused filter/project stage so a blocking
        consumer (agg/sort/topn) can run it inside its own kernel."""
        prev = chain[-1] if chain else None
        pf = getattr(prev, "fused_fn", None)
        if pf is not None:
            chain.pop()
        return pf

    def _visit_RemoteSourceNode(self, node: P.RemoteSourceNode):
        """Exchange client as a source operator (ExchangeOperator.java:44;
        with merge_keys, MergeOperator.java:46). The execution context
        provides "make_remote_source": (fragment_ids) -> page source."""
        from trino_tpu.exec.exchange_ops import RemoteSourceOperator

        schemas = [self.remote_schemas[fid] for fid in node.fragment_ids]
        assert schemas and all(
            [t for t, _ in s] == [t for t, _ in schemas[0]] for s in schemas
        ), "remote source fragments must share one schema"
        schema: Schema = schemas[0]
        fragment_ids = tuple(node.fragment_ids)
        merge_keys = list(node.merge_keys) if node.merge_keys else None
        ladder = self.stabilizer.ladder if self.stabilizer is not None else None
        return [
            lambda ctx: RemoteSourceOperator(
                ctx["make_remote_source"](fragment_ids), merge_keys,
                ladder=ladder,
            )
        ], schema

    def _visit_FilterNode(self, node: P.FilterNode):
        chain, schema = self._visit(node.child)
        flt = self._bind(node.predicate, schema)
        fn = self._cached_fp(
            flt, self._identity(schema), schema, ("flt", repr(node.predicate))
        )
        self._append_fp(chain, fn, schema, schema)
        return chain, schema

    def _visit_ProjectNode(self, node: P.ProjectNode):
        # fuse a Filter directly below (ScanFilterAndProject discipline)
        child = node.child
        flt = None
        if isinstance(child, P.FilterNode):
            chain, schema = self._visit(child.child)
            flt = self._bind(child.predicate, schema)
        else:
            chain, schema = self._visit(child)
        bounds = [self._bind(e, schema) for e in node.exprs]
        fingerprint = (
            "proj",
            repr(child.predicate) if flt is not None else None,
            tuple(repr(e) for e in node.exprs),
        )
        fn = self._cached_fp(flt, bounds, schema, fingerprint)
        out_schema: Schema = [(b.type, b.dictionary) for b in bounds]
        self._append_fp(chain, fn, schema, out_schema)
        return chain, out_schema

    def _visit_AggregateNode(self, node: P.AggregateNode):
        mxu = self._try_mxu_join_agg(node)
        if mxu is not None:
            return mxu
        chain, schema = self._visit(node.child)
        if any(a.distinct for a in node.aggs):
            return self._distinct_agg(node, chain, schema)
        specs = [
            AggSpec(a.kind, a.arg_channel, a.out_type,
                    arg2_channel=a.arg2_channel, percentile=a.percentile,
                    separator=a.separator, arg3_channel=a.arg3_channel,
                    param=a.param, post=a.post)
            for a in node.aggs
        ]
        groups = list(node.group_channels)
        step = node.step
        # input capacity classes before the fused stage is absorbed
        # (filter/project preserves capacity, so they flow through)
        src_caps = getattr(chain[-1], "out_caps", None) if chain else None
        pre = self._take_fused(chain)
        chain.append(
            lambda ctx: HashAggregationOperator(
                groups, specs, schema, step=step, memory_context=_mem_ctx(ctx),
                deferred_checks=ctx.setdefault("deferred_checks", []),
                pre_fn=pre,
            )
        )
        if step == "partial":
            from trino_tpu.exec.operators import partial_output_schema

            out_schema = partial_output_schema(specs, groups, schema)
            self._record_kernel_warmup(
                "HashAggregationOperator",
                _AggWarmer(groups, specs, schema, step),
                schema, out_schema, src_caps,
            )
            return chain, out_schema
        # min/max/any and the holistic kinds return a value from the
        # argument column, so its dictionary must ride along (a string
        # result without its dictionary renders as raw codes)
        def _out_dict(a):
            if (
                a.kind in ("min", "max", "any", "min_by", "max_by",
                           "approx_percentile")
                and a.arg_channel is not None
            ):
                return schema[a.arg_channel][1]
            if a.kind == "listagg":
                # created at execution time; plan-time string ops over
                # it must fail loudly (expr/compile._null_of)
                from trino_tpu.block import RuntimeDictionary

                return RuntimeDictionary()
            return None

        out_schema: Schema = [schema[c] for c in node.group_channels] + [
            (a.out_type, _out_dict(a)) for a in node.aggs
        ]
        if step == "final":
            # keys and min/max/any results keep the dictionaries that
            # rode through the state wire format
            out_schema = [schema[c] for c in range(len(groups))] + [
                (a.out_type, schema[len(groups) + 2 * i][1])
                for i, a in enumerate(node.aggs)
            ]
        self._record_kernel_warmup(
            "HashAggregationOperator",
            _AggWarmer(groups, specs, schema, step),
            schema, out_schema, src_caps,
        )
        return chain, out_schema

    def _try_mxu_join_agg(self, node: P.AggregateNode):
        """MXU join-project selection (ops/mxu_join.py): a single-step
        grouped aggregate directly over an inner single-integer-key
        equi-join, all group columns build-side, all aggregate
        arguments probe-side (or COUNT(*)), kinds in sum/count — the
        shape where the pair sum factors through the key and the join
        never needs to expand. Returns (chain, schema) when selected,
        None to fall through to the standard agg-over-join plan."""
        if not self.mxu_join:
            return None
        # the column pruner routinely leaves an identity Project
        # (pure channel references) between the aggregate and the
        # join — look through it, composing the channel map
        join = node.child
        cmap: Optional[List[int]] = None
        while isinstance(join, P.ProjectNode) and all(
            isinstance(e, InputRef) for e in join.exprs
        ):
            m = [e.index for e in join.exprs]
            cmap = m if cmap is None else [m[c] for c in cmap]
            join = join.child
        if not isinstance(join, P.JoinNode):
            return None

        def tr(ch: int) -> int:
            return cmap[ch] if cmap is not None else ch

        if (
            join.kind != "inner"
            or join.residual is not None
            or len(join.left_keys) != 1
            or len(join.right_keys) != 1
            or getattr(join, "spill_build", False)
        ):
            return None
        if node.step != "single" or not node.group_channels or not node.aggs:
            return None
        probe_width = len(join.left.fields)
        if any(tr(ch) < probe_width for ch in node.group_channels):
            return None
        for side, ch in ((join.left, join.left_keys[0]),
                         (join.right, join.right_keys[0])):
            t = side.fields[ch].type
            if t.is_nested or t.lanes != 1 or not t.is_integerlike:
                return None
        for a in node.aggs:
            if a.kind not in ("sum", "count", "count_star") or a.distinct:
                return None
            if (
                a.arg2_channel is not None or a.arg3_channel is not None
                or a.post is not None or a.out_type != T.BIGINT
            ):
                return None
            if a.kind == "count_star":
                if a.arg_channel is not None:
                    return None
                continue
            if a.arg_channel is None or tr(a.arg_channel) >= probe_width:
                return None
            at = join.left.fields[tr(a.arg_channel)].type
            if at.is_nested or at.lanes != 1 or not at.is_integerlike:
                return None
        # work gate: expected pairs per probe row (fanout) x build key
        # NDV must clear the threshold — below it the expansion is
        # cheap and the standard join keeps its dynamic-filter and
        # warmup advantages
        try:
            if self._stats_calc is None:
                from trino_tpu.sql.stats import StatsCalculator

                self._stats_calc = StatsCalculator(self.catalogs)
            bs = self._stats_calc.stats(join.right)
            rows = float(bs.row_count or 0.0)
            ndv = float(bs.col(join.right_keys[0]).ndv or rows)
            fanout = rows / max(ndv, 1.0)
            if fanout * ndv < self.mxu_join_min_work:
                return None
        except Exception:
            return None

        build_chain, build_schema = self._visit(join.right)
        probe_chain, probe_schema = self._visit(join.left)
        key = self._key()

        def bridge_of(ctx) -> JoinBridge:
            return ctx.setdefault(key, JoinBridge())

        rkeys = [join.right_keys[0]]
        # no memory context: this path has no grace-mode probe, so the
        # build sink must never flip to spill under pool pressure
        build_chain.append(
            lambda ctx: HashBuildSink(bridge_of(ctx), rkeys, build_schema)
        )
        self.pipelines.append(build_chain)
        lkey = join.left_keys[0]
        aggs = [
            dataclasses.replace(a, arg_channel=tr(a.arg_channel))
            if a.arg_channel is not None else a
            for a in node.aggs
        ]
        groups_b = [tr(ch) - probe_width for ch in node.group_channels]
        probe_chain.append(
            lambda ctx: MxuJoinAggOperator(bridge_of(ctx), lkey, aggs, groups_b)
        )
        # final grouping over the per-build-row partials: SUM of each
        # partial column (NULL partials drop out, so SUM-over-only-NULLs
        # is NULL and COUNT partials — always valid — total exactly)
        g = len(groups_b)
        partial_schema: Schema = [build_schema[ch] for ch in groups_b] + [
            (a.out_type, None) for a in aggs
        ]
        specs = [
            AggSpec("sum", g + i, a.out_type) for i, a in enumerate(aggs)
        ]
        probe_chain.append(
            lambda ctx: HashAggregationOperator(
                list(range(g)), specs, partial_schema,
                memory_context=_mem_ctx(ctx),
            )
        )
        from trino_tpu.runtime.metrics import METRICS

        METRICS.increment("skew.mxu_join_selected")
        out_schema: Schema = partial_schema[:g] + [
            (a.out_type, None) for a in aggs
        ]
        return probe_chain, out_schema

    def _distinct_agg(self, node: P.AggregateNode, chain, schema: Schema):
        """DISTINCT aggregates via dedup-then-aggregate (the
        MarkDistinct/MultipleDistinctAggregationToMarkDistinct analogue,
        restricted to the single-distinct shape)."""
        if len(node.aggs) != 1:
            raise NotImplementedError(
                "DISTINCT aggregates must be the only aggregate"
            )
        a = node.aggs[0]
        if a.arg_channel is None:
            raise NotImplementedError("count(distinct *) is meaningless")
        dedup_channels = list(node.group_channels) + [a.arg_channel]
        chain.append(
            lambda ctx: HashAggregationOperator(dedup_channels, [], schema)
        )
        dedup_schema: Schema = [schema[c] for c in dedup_channels]
        k = len(node.group_channels)
        specs = [AggSpec(a.kind, k, a.out_type)]
        groups = list(range(k))
        chain.append(
            lambda ctx: HashAggregationOperator(groups, specs, dedup_schema)
        )
        out_schema: Schema = dedup_schema[:k] + [(a.out_type, None)]
        return chain, out_schema

    def _visit_JoinNode(self, node: P.JoinNode):
        build_chain, build_schema = self._visit(node.right)
        probe_chain, probe_schema = self._visit(node.left)
        build_caps = (
            getattr(build_chain[-1], "out_caps", None) if build_chain
            else None
        )
        probe_caps = (
            getattr(probe_chain[-1], "out_caps", None) if probe_chain
            else None
        )
        key = self._key()

        def bridge_of(ctx) -> JoinBridge:
            return ctx.setdefault(key, JoinBridge())

        if node.kind == "cross":
            build_chain.append(
                lambda ctx: CrossJoinBuildSink(bridge_of(ctx), build_schema)
            )
            self.pipelines.append(build_chain)
            probe_chain.append(lambda ctx: CrossJoinOperator(bridge_of(ctx)))
            return probe_chain, probe_schema + build_schema
        rkeys = list(node.right_keys)
        # adaptive spill-mode annotation (skewed/oversized build side):
        # grace partitions open before the first batch arrives
        force_spill = bool(getattr(node, "spill_build", False))
        build_chain.append(
            lambda ctx: HashBuildSink(
                bridge_of(ctx), rkeys, build_schema,
                memory_context=_mem_ctx(ctx), force_spill=force_spill,
            )
        )
        self.pipelines.append(build_chain)
        residual_fn = None
        if node.residual is not None:
            residual_fn = make_residual_fn(
                self._bind(node.residual, probe_schema + build_schema)
            )
        lkeys = list(node.left_keys)
        kind = node.kind
        if kind in ("inner", "semi") and self.dynamic_filtering:
            from trino_tpu.exec.operators import DynamicFilterOperator

            # connector reuse: when the probe side is a bare scan, feed
            # the build-side key domains into the scan's split handles
            # (evaluated lazily at first probe page — the build pipeline
            # has completed by then) so parquet row-group pruning and
            # constraint masks apply to dynamic-filter bounds too. The
            # DynamicFilterOperator below still enforces, so an
            # unpopulated bridge only costs the pruning, never rows.
            if isinstance(node.left, P.ScanNode) and len(probe_chain) == 1:
                from trino_tpu.exec.operators import (
                    dynamic_filter_constraints,
                )

                scan = node.left
                key_names = [scan.columns[c] for c in lkeys]
                key_types = [scan.fields[c].type for c in lkeys]
                scan_factory = probe_chain[0]

                def df_scan_factory(ctx, _f=scan_factory):
                    op = _f(ctx)
                    if hasattr(op, "set_runtime_constraints"):
                        op.set_runtime_constraints(
                            lambda: dynamic_filter_constraints(
                                bridge_of(ctx), key_types, key_names
                            )
                        )
                    return op

                caps = getattr(scan_factory, "out_caps", None)
                if caps is not None:
                    df_scan_factory.out_caps = caps
                probe_chain[0] = df_scan_factory
            probe_chain.append(
                lambda ctx: DynamicFilterOperator(bridge_of(ctx), lkeys)
            )
        probe_chain.append(
            lambda ctx: LookupJoinOperator(
                bridge_of(ctx), lkeys, kind, probe_schema,
                residual_fn=residual_fn,
            )
        )
        if node.kind in ("semi", "anti"):
            out_schema = probe_schema
        elif node.kind in ("mark", "mark_exists"):
            out_schema = probe_schema + [(T.BOOLEAN, None)]
        else:
            out_schema = probe_schema + build_schema
        # residual joins skip: the residual program binds to this plan's
        # expressions, which the dead-batch warmer does not replicate
        if probe_caps and build_caps and residual_fn is None:
            self._record_kernel_warmup(
                "LookupJoinOperator",
                _JoinWarmer(lkeys, rkeys, kind, probe_schema,
                            build_schema, build_caps[0]),
                probe_schema, out_schema, probe_caps,
            )
        return probe_chain, out_schema

    def _visit_WindowNode(self, node: P.WindowNode):
        from trino_tpu.exec.operators import WindowOperator

        chain, schema = self._visit(node.child)
        partition = list(node.partition_channels)
        order = list(node.order_keys)
        fns = list(node.functions)
        frame = node.frame
        chain.append(
            lambda ctx: WindowOperator(partition, order, fns, frame, schema)
        )
        out_schema: Schema = list(schema)
        for f in fns:
            d = None
            if f.arg_channel is not None and f.kind in (
                "lead", "lag", "first_value", "last_value", "min", "max"
            ):
                d = schema[f.arg_channel][1]
            out_schema.append((f.out_type, d))
        return chain, out_schema

    def _visit_EnforceSingleRowNode(self, node: P.EnforceSingleRowNode):
        from trino_tpu.exec.operators import EnforceSingleRowOperator

        chain, schema = self._visit(node.child)
        chain.append(lambda ctx: EnforceSingleRowOperator(schema))
        return chain, schema

    def _visit_UnnestNode(self, node: P.UnnestNode):
        from trino_tpu.exec.unnest import UnnestOperator

        chain, schema = self._visit(node.child)
        channels = list(node.array_channels)
        ordinality = node.ordinality
        chain.append(
            lambda ctx: UnnestOperator(channels, ordinality, schema)
        )
        out_schema: Schema = list(schema)
        for ch in channels:
            elem_t = schema[ch][0].element
            out_schema.append((elem_t, schema[ch][1]))
        if ordinality:
            out_schema.append((T.BIGINT, None))
        return chain, out_schema

    def _visit_MatchRecognizeNode(self, node: P.MatchRecognizeNode):
        from trino_tpu.exec.match_recognize import MatchRecognizeOperator

        chain, schema = self._visit(node.child)
        # bind DEFINE predicates over the extended schema (child +
        # shifted copies); evaluation is one device program per define,
        # fused by XLA (exec/match_recognize.py)
        ext_schema: Schema = list(schema) + [
            schema[ch] for ch, _off in node.shifts
        ]
        define_fns = [
            (var, self._bind(pred, ext_schema).fn)
            for var, pred in node.defines
        ]
        chain.append(
            lambda ctx: MatchRecognizeOperator(node, schema, define_fns)
        )
        out_schema: Schema = []
        for ch in node.partition_channels:
            out_schema.append(schema[ch])
        for m in node.measures:
            if m.kind == "classifier":
                out_schema.append((m.out_type, None))  # runtime dict
            elif m.channel is not None:
                out_schema.append((m.out_type, schema[m.channel][1]))
            else:
                out_schema.append((m.out_type, None))
        return chain, out_schema

    def _visit_SortNode(self, node: P.SortNode):
        chain, schema = self._visit(node.child)
        keys = list(node.keys)
        pre = self._take_fused(chain)
        chain.append(
            lambda ctx: SortOperator(
                keys, schema, memory_context=_mem_ctx(ctx), pre_fn=pre
            )
        )
        return chain, schema

    def _visit_TopNNode(self, node: P.TopNNode):
        chain, schema = self._visit(node.child)
        keys = list(node.keys)
        count = node.count
        pre = self._take_fused(chain)
        chain.append(lambda ctx: TopNOperator(keys, count, schema, pre_fn=pre))
        return chain, schema

    def _visit_LimitNode(self, node: P.LimitNode):
        chain, schema = self._visit(node.child)
        count, offset = node.count, node.offset
        chain.append(lambda ctx: LimitOperator(count, offset))
        return chain, schema

    def _visit_UnionAllNode(self, node: P.UnionAllNode):
        sink_keys = []
        schemas = []
        for child in node.inputs:
            chain, schema = self._visit(child)
            schemas.append(schema)
            key = self._key()
            sink_keys.append(key)
            chain.append(
                lambda ctx, key=key: ctx.setdefault(key, BufferSink())
            )
            self.pipelines.append(chain)
        # string columns must agree on dictionaries across inputs for the
        # shared buffer to be bindable downstream; an all-NULL input
        # (None/empty dictionary, e.g. grouping-set NULL keys) is
        # compatible with anything
        def _dict_rank(d):
            return 0 if d is None or len(d) == 0 else 1

        out_schema = list(schemas[0])
        for s in schemas[1:]:
            for i, ((t0, d0), (t1, d1)) in enumerate(zip(out_schema, s)):
                if not t0.is_string:
                    continue
                if _dict_rank(d0) == 0:
                    out_schema[i] = (t0, d1)
                elif _dict_rank(d1) == 0 or d0 == d1:
                    continue
                else:
                    raise NotImplementedError(
                        "UNION of string columns with differing dictionaries"
                    )
        return [
            lambda ctx: BufferSource([ctx[k] for k in sink_keys])
        ], out_schema
