"""SQL AST.

Analogue of trino-parser's tree package (261 node classes in
parser/sql/tree/ — SURVEY.md §2.1), reduced to the analytic-SQL subset
the engine executes (TPC-H/TPC-DS-shaped queries first). Nodes are
frozen dataclasses; the analyzer never mutates them.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple


class Node:
    pass


class Expression(Node):
    pass


# ---------------------------------------------------------------------------
# literals & leaves
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Identifier(Expression):
    """Possibly-qualified name: parts = ("l", "quantity") for l.quantity."""

    parts: Tuple[str, ...]

    def __str__(self):
        return ".".join(self.parts)


@dataclasses.dataclass(frozen=True)
class NumberLiteral(Expression):
    text: str  # original text; analyzer decides integer vs decimal vs double


@dataclasses.dataclass(frozen=True)
class StringLiteral(Expression):
    value: str


@dataclasses.dataclass(frozen=True)
class BooleanLiteral(Expression):
    value: bool


@dataclasses.dataclass(frozen=True)
class NullLiteral(Expression):
    pass


@dataclasses.dataclass(frozen=True)
class DateLiteral(Expression):
    value: str  # 'YYYY-MM-DD'


@dataclasses.dataclass(frozen=True)
class TimestampLiteral(Expression):
    value: str


@dataclasses.dataclass(frozen=True)
class IntervalLiteral(Expression):
    value: str
    unit: str  # day/month/year
    sign: int = 1


@dataclasses.dataclass(frozen=True)
class AtTimeZone(Expression):
    """expr AT TIME ZONE zone (parser/sql/tree/AtTimeZone.java)."""

    operand: Expression
    zone: Expression


@dataclasses.dataclass(frozen=True)
class Star(Expression):
    """`*` or `alias.*` in a select list or count(*)."""

    qualifier: Optional[str] = None


# ---------------------------------------------------------------------------
# compound expressions
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BinaryOp(Expression):
    op: str  # and or + - * / % = <> < <= > >=
    left: Expression
    right: Expression


@dataclasses.dataclass(frozen=True)
class UnaryOp(Expression):
    op: str  # not, -, +
    operand: Expression


@dataclasses.dataclass(frozen=True)
class IsNullPredicate(Expression):
    operand: Expression
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Between(Expression):
    value: Expression
    low: Expression
    high: Expression
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InList(Expression):
    value: Expression
    options: Tuple[Expression, ...]
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InSubquery(Expression):
    value: Expression
    query: "Query"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Exists(Expression):
    query: "Query"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class ScalarSubquery(Expression):
    query: "Query"


@dataclasses.dataclass(frozen=True)
class Like(Expression):
    value: Expression
    pattern: Expression
    escape: Optional[Expression] = None
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class FunctionCall(Expression):
    name: str
    args: Tuple[Expression, ...]
    distinct: bool = False


@dataclasses.dataclass(frozen=True)
class WindowSpec(Node):
    """OVER (PARTITION BY ... ORDER BY ... [frame]) — parser/sql/tree/
    WindowSpecification analogue. frame: "range" (default: current row
    + peers), "rows" (UNBOUNDED PRECEDING..CURRENT ROW) or "partition"
    (UNBOUNDED..UNBOUNDED, or no ORDER BY)."""

    partition_by: Tuple[Expression, ...] = ()
    order_by: Tuple["SortItem", ...] = ()
    frame: str = "range"


@dataclasses.dataclass(frozen=True)
class WindowCall(Expression):
    """A window function invocation: fn(args) OVER spec. Deliberately a
    separate node from FunctionCall so aggregate detection never
    confuses sum(x) OVER (...) with the aggregate sum(x)."""

    name: str
    args: Tuple[Expression, ...]
    spec: WindowSpec


@dataclasses.dataclass(frozen=True)
class Extract(Expression):
    field: str  # year/month/day
    operand: Expression


@dataclasses.dataclass(frozen=True)
class TypeName(Node):
    name: str
    params: Tuple[int, ...] = ()
    # nested type arguments: ((field_name | None, TypeName), ...) for
    # array(T) / map(K, V) / row(name T, ...)
    args: Tuple = ()


@dataclasses.dataclass(frozen=True)
class Cast(Expression):
    operand: Expression
    target: TypeName


@dataclasses.dataclass(frozen=True)
class WhenClause(Node):
    condition: Expression
    result: Expression


@dataclasses.dataclass(frozen=True)
class Case(Expression):
    """Searched or simple CASE (operand set for the simple form)."""

    operand: Optional[Expression]
    whens: Tuple[WhenClause, ...]
    default: Optional[Expression]


# ---------------------------------------------------------------------------
# relations
# ---------------------------------------------------------------------------


class Relation(Node):
    pass


@dataclasses.dataclass(frozen=True)
class TableRef(Relation):
    """catalog.schema.table with optional alias."""

    name: Tuple[str, ...]
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class SubqueryRelation(Relation):
    """Derived table, optionally with derived column aliases:
    `(query) AS t(c1, c2)` (SqlBase.g4 aliasedRelation/columnAliases)."""

    query: "Query"
    alias: Optional[str] = None
    column_aliases: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Join(Relation):
    kind: str  # inner/left/right/full/cross
    left: Relation
    right: Relation
    condition: Optional[Expression] = None  # ON expr; None for CROSS
    using: Tuple[str, ...] = ()


# ---------------------------------------------------------------------------
# query structure
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SelectItem(Node):
    expr: Expression
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class SortItem(Node):
    expr: Expression
    descending: bool = False
    nulls_first: Optional[bool] = None  # None = SQL default (last for ASC)


@dataclasses.dataclass(frozen=True)
class QuerySpec(Node):
    select: Tuple[SelectItem, ...]
    distinct: bool = False
    from_: Optional[Relation] = None
    where: Optional[Expression] = None
    group_by: Tuple[Expression, ...] = ()
    having: Optional[Expression] = None
    # GROUPING SETS/ROLLUP/CUBE: index tuples into group_by (None =
    # plain GROUP BY over all of group_by)
    group_by_sets: Optional[Tuple[Tuple[int, ...], ...]] = None


@dataclasses.dataclass(frozen=True)
class SetOperation(Node):
    """UNION/INTERSECT/EXCEPT [ALL|DISTINCT] of two query bodies."""

    op: str  # union/intersect/except
    all: bool
    left: Node  # QuerySpec | SetOperation
    right: Node


@dataclasses.dataclass(frozen=True)
class WithQuery(Node):
    name: str
    query: "Query"
    column_names: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Query(Node):
    body: Node  # QuerySpec | SetOperation
    with_: Tuple[WithQuery, ...] = ()
    order_by: Tuple[SortItem, ...] = ()
    limit: Optional[int] = None
    offset: int = 0


# other statements
@dataclasses.dataclass(frozen=True)
class Parameter(Expression):
    """`?` placeholder in a prepared statement (tree/Parameter.java);
    EXECUTE ... USING substitutes literals positionally before
    analysis."""

    index: int


@dataclasses.dataclass(frozen=True)
class Prepare(Node):
    """PREPARE name FROM <statement> (tree/Prepare.java:25)."""

    name: str
    statement: "Node"
    sql: str  # original statement text (SHOW/DESCRIBE surfaces)


@dataclasses.dataclass(frozen=True)
class ExecuteStmt(Node):
    """EXECUTE name [USING expr, ...] (tree/Execute.java)."""

    name: str
    parameters: Tuple[Expression, ...]


@dataclasses.dataclass(frozen=True)
class Deallocate(Node):
    """DEALLOCATE PREPARE name (tree/Deallocate.java)."""

    name: str


@dataclasses.dataclass(frozen=True)
class ExplainStatement(Node):
    query: Query
    analyze: bool = False


@dataclasses.dataclass(frozen=True)
class ValuesBody(Node):
    """VALUES (...), (...) as a query body."""

    rows: Tuple[Tuple[Expression, ...], ...]


@dataclasses.dataclass(frozen=True)
class Lambda(Expression):
    """`x -> expr` / `(x, y) -> expr` — argument to higher-order
    functions (parser/sql/tree/LambdaExpression.java analogue)."""

    params: Tuple[str, ...]
    body: "Expression"


@dataclasses.dataclass(frozen=True)
class ArrayLiteral(Expression):
    """ARRAY[e1, e2, ...]."""

    elements: Tuple[Expression, ...]


@dataclasses.dataclass(frozen=True)
class Subscript(Expression):
    """Postfix element access: a[i] (array) / m[k] (map)."""

    operand: Expression
    index: Expression


@dataclasses.dataclass(frozen=True)
class UnnestRelation(Relation):
    """UNNEST(a1, a2, ...) [WITH ORDINALITY] [AS t(c1, ...)] — zips the
    arrays into rows (UnnestOperator analogue, main/operator/unnest/)."""

    arrays: Tuple[Expression, ...]
    ordinality: bool = False
    alias: Optional[str] = None
    column_aliases: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class MeasureItem(Node):
    """One MEASURES entry: expr AS name."""

    expr: Expression
    name: str


@dataclasses.dataclass(frozen=True)
class MatchRecognizeRelation(Relation):
    """<relation> MATCH_RECOGNIZE (...) — SQL row pattern recognition
    (SqlBase.g4 patternRecognition; main/operator/window/pattern/).
    `pattern` is a small tuple AST: ("var", name) | ("seq", [...]) |
    ("alt", [...]) | ("star"|"plus"|"opt", node) | ("rep", node, n, m)."""

    input: Relation
    partition_by: Tuple[Expression, ...] = ()
    order_by: Tuple["SortItem", ...] = ()
    measures: Tuple[MeasureItem, ...] = ()
    rows_per_match: str = "one"  # "one" | "all"
    after_match: str = "past_last"  # "past_last" | "next_row"
    pattern: object = None
    defines: Tuple[Tuple[str, Expression], ...] = ()
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class Descriptor(Expression):
    """DESCRIPTOR(name, ...) — a column-name list argument to a table
    function (spi/ptf Descriptor analogue)."""

    names: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class TableArg(Expression):
    """TABLE(relation) argument to a polymorphic table function."""

    relation: Relation


@dataclasses.dataclass(frozen=True)
class TableFunctionRelation(Relation):
    """FROM TABLE(fn(arg, name => arg, ...)) — the SQL-standard
    table-function invocation (SqlBase.g4 tableFunctionCall;
    spi/ptf/ConnectorTableFunction analogue)."""

    name: Tuple[str, ...]
    args: Tuple[Expression, ...] = ()
    named_args: Tuple[Tuple[str, Expression], ...] = ()
    alias: Optional[str] = None
    column_aliases: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class CreateTable(Node):
    table: Tuple[str, ...]
    columns: Tuple[Tuple[str, TypeName], ...]


@dataclasses.dataclass(frozen=True)
class CreateTableAs(Node):
    table: Tuple[str, ...]
    query: "Query"


@dataclasses.dataclass(frozen=True)
class Insert(Node):
    table: Tuple[str, ...]
    columns: Optional[Tuple[str, ...]]
    query: "Query"


@dataclasses.dataclass(frozen=True)
class DropTable(Node):
    table: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class SetSession(Node):
    name: str
    value: str  # literal text; engine validates via the property registry


@dataclasses.dataclass(frozen=True)
class Delete(Node):
    """DELETE FROM table [WHERE predicate]."""

    table: Tuple[str, ...]
    where: Optional[Expression] = None


@dataclasses.dataclass(frozen=True)
class Update(Node):
    """UPDATE table SET col = expr [, ...] [WHERE predicate]."""

    table: Tuple[str, ...]
    assignments: Tuple[Tuple[str, Expression], ...]
    where: Optional[Expression] = None


@dataclasses.dataclass(frozen=True)
class MergeClause(Node):
    """One WHEN [NOT] MATCHED [AND cond] THEN action arm
    (parser/sql/tree/MergeCase.java subclasses)."""

    matched: bool
    condition: Optional[Expression]
    action: str  # "update" | "delete" | "insert"
    assignments: Tuple[Tuple[str, Expression], ...] = ()
    insert_columns: Optional[Tuple[str, ...]] = None
    insert_values: Tuple[Expression, ...] = ()


@dataclasses.dataclass(frozen=True)
class Merge(Node):
    """MERGE INTO target USING source ON cond WHEN ... THEN ...
    (parser/sql/tree/Merge.java)."""

    table: Tuple[str, ...]
    target_alias: Optional[str]
    source: Relation
    on: Expression
    clauses: Tuple[MergeClause, ...]


@dataclasses.dataclass(frozen=True)
class StartTransaction(Node):
    """START TRANSACTION [READ ONLY | READ WRITE] (isolation modes are
    accepted and ignored — the reference's connectors mostly run
    read-committed-at-best anyway)."""

    read_only: bool = False


@dataclasses.dataclass(frozen=True)
class Commit(Node):
    pass


@dataclasses.dataclass(frozen=True)
class Rollback(Node):
    pass


@dataclasses.dataclass(frozen=True)
class ShowSession(Node):
    pass


@dataclasses.dataclass(frozen=True)
class ShowTables(Node):
    schema: Optional[Tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class ShowSchemas(Node):
    catalog: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class ShowColumns(Node):
    table: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class ShowFunctions(Node):
    pass


def substitute_parameters(node, values):
    """Positionally replace Parameter placeholders with literal
    expressions (EXECUTE ... USING binding — the analyzer rejects any
    Parameter that survives)."""
    import dataclasses as _dc

    def sub(x):
        if isinstance(x, Parameter):
            if x.index >= len(values):
                raise ValueError(
                    f"prepared statement needs {x.index + 1} parameters, "
                    f"got {len(values)}"
                )
            return values[x.index]
        if _dc.is_dataclass(x) and isinstance(x, Node):
            changes = {}
            for f in _dc.fields(x):
                v = getattr(x, f.name)
                nv = sub(v)
                if nv is not v:
                    changes[f.name] = nv
            return _dc.replace(x, **changes) if changes else x
        if isinstance(x, tuple):
            out = tuple(sub(e) for e in x)
            return out if any(a is not b for a, b in zip(out, x)) else x
        return x

    return sub(node)
