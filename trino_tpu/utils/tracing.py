"""Tracing spans.

Analogue of the reference's OpenTelemetry integration (main/tracing/
TracingMetadata.java:106, ScopedSpan, spans per planning phase —
SqlQueryExecution.java:459–462; SURVEY.md §5.1), reduced to an
in-process recorder with the same span tree shape: a query span with
parse/analyze/plan/schedule/execute children, exportable as JSON. An
OTLP exporter slots in behind `Tracer.export` without touching call
sites."""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class Span:
    name: str
    start_s: float
    end_s: Optional[float] = None
    attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)
    children: List["Span"] = dataclasses.field(default_factory=list)

    @property
    def duration_s(self) -> float:
        return (self.end_s or time.monotonic()) - self.start_s

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "duration_ms": round(self.duration_s * 1000, 3),
            "attributes": dict(self.attributes),
            "children": [c.to_dict() for c in self.children],
        }


class Tracer:
    """Per-thread span stack; roots are retained for export."""

    def __init__(self):
        self._local = threading.local()
        self._roots: List[Span] = []
        self._lock = threading.Lock()

    def _stack(self) -> List[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        s = Span(name, time.monotonic(), attributes=dict(attributes))
        stack = self._stack()
        if stack:
            stack[-1].children.append(s)
        else:
            with self._lock:
                self._roots.append(s)
        stack.append(s)
        try:
            yield s
        finally:
            s.end_s = time.monotonic()
            stack.pop()

    def roots(self) -> List[Span]:
        with self._lock:
            return list(self._roots)

    def export(self) -> List[dict]:
        return [r.to_dict() for r in self.roots()]

    def clear(self) -> None:
        with self._lock:
            self._roots.clear()


# process-wide default tracer (the GlobalOpenTelemetry stand-in)
TRACER = Tracer()
