"""Pure-python raw Snappy codec (the parquet SNAPPY page codec).

The format (google/snappy format_description.txt — a public spec, like
the XXH64/murmur3 implementations in expr/pyfns.py): a varint
uncompressed length, then tagged elements — literals (tag 00) and
back-references (tags 01/10/11 with 1/2/4-byte offsets). The
compressor is the standard greedy 4-byte-hash matcher; output is valid
Snappy any decoder accepts. Pages are small (row-group column chunks),
so pure python keeps the no-external-deps property of the parquet
codec without a native build."""

from __future__ import annotations


def _uvarint(data: bytes, pos: int):
    x = shift = 0
    while True:
        b = data[pos]
        pos += 1
        x |= (b & 0x7F) << shift
        if not b & 0x80:
            return x, pos
        shift += 7


def _put_uvarint(x: int) -> bytes:
    out = bytearray()
    while x >= 0x80:
        out.append((x & 0x7F) | 0x80)
        x >>= 7
    out.append(x)
    return bytes(out)


def decompress(data: bytes) -> bytes:
    n, pos = _uvarint(data, 0)
    out = bytearray()
    ln = len(data)
    while pos < ln:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            length = tag >> 2
            if length >= 60:
                extra = length - 59
                length = int.from_bytes(
                    data[pos:pos + extra], "little"
                )
                pos += extra
            length += 1
            out += data[pos:pos + length]
            pos += length
            continue
        if kind == 1:  # copy, 1-byte offset
            length = ((tag >> 2) & 0x7) + 4
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if offset == 0:
            raise ValueError("snappy: zero copy offset")
        start = len(out) - offset
        if start < 0:
            raise ValueError("snappy: offset before stream start")
        if offset >= length:
            # non-overlapping: one slice copy
            out += out[start:start + length]
        else:
            # overlapping run: double a seed slice (byte-replication
            # semantics) instead of a per-byte python loop
            seed = bytes(out[start:])
            while len(seed) < length:
                seed = seed + seed
            out += seed[:length]
    if len(out) != n:
        raise ValueError(
            f"snappy: length mismatch ({len(out)} != {n})"
        )
    return bytes(out)


def _emit_literal(out: bytearray, data: bytes, start: int, end: int):
    length = end - start
    if length <= 0:
        return
    length -= 1
    if length < 60:
        out.append(length << 2)
    else:
        nbytes = (length.bit_length() + 7) // 8
        out.append(((59 + nbytes) << 2))
        out += length.to_bytes(nbytes, "little")
    out += data[start:end]


def _emit_copy(out: bytearray, offset: int, length: int):
    while length > 0:
        cur = min(length, 64)
        if cur < 4:
            # tags encode >= 4 (1-byte) or 1..64 (2-byte); short tails
            # use the 2-byte form
            out.append(((cur - 1) << 2) | 2)
            out += offset.to_bytes(2, "little")
        elif cur <= 11 and offset < 2048:
            out.append(
                ((offset >> 8) << 5) | ((cur - 4) << 2) | 1
            )
            out.append(offset & 0xFF)
        else:
            out.append(((cur - 1) << 2) | 2)
            out += offset.to_bytes(2, "little")
        length -= cur


def compress(data: bytes) -> bytes:
    n = len(data)
    out = bytearray(_put_uvarint(n))
    if n < 4:
        _emit_literal(out, data, 0, n)
        return bytes(out)
    table: dict = {}
    pos = 0
    lit_start = 0
    limit = n - 4
    while pos <= limit:
        key = data[pos:pos + 4]
        cand = table.get(key)
        table[key] = pos
        if cand is not None and pos - cand <= 0xFFFF:
            # extend the match
            length = 4
            while (
                pos + length < n
                and data[cand + length] == data[pos + length]
                and length < 1 << 16
            ):
                length += 1
            _emit_literal(out, data, lit_start, pos)
            _emit_copy(out, pos - cand, length)
            pos += length
            lit_start = pos
        else:
            pos += 1
    _emit_literal(out, data, lit_start, n)
    return bytes(out)
