"""In-process query engine: SQL text in, rows out.

Analogue of Trino's LocalQueryRunner (main/testing/LocalQueryRunner.java:264
— plan and execute SQL fully in-process with real operators, SURVEY.md
§4.2) plus the session/catalog surface of Session + MetadataManager.
The distributed runner (coordinator/worker split over fragments) layers
on top of the same plans.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

from trino_tpu import types as T
from trino_tpu.connectors.spi import CatalogManager, Connector
from trino_tpu.exec import CollectorSink, Driver, Pipeline
from trino_tpu.sql import ast
from trino_tpu.sql.analyzer import AnalysisError, Analyzer
from trino_tpu.sql.local_planner import LocalPlanner
from trino_tpu.sql.parser import parse
from trino_tpu.sql.plan import OutputNode, explain_text


@dataclasses.dataclass
class Session:
    """Per-query context (main/Session.java analogue; properties grow
    with the session-property system). retry_policy mirrors Trino's
    `retry_policy` session property: "none" (pipelined), "query"
    (whole-query retry inside the pipelined scheduler,
    PipelinedQueryScheduler.scheduleRetryWithDelay:394) or "task"
    (FTE over spooled exchange, SURVEY.md §3.5)."""

    catalog: str = "tpch"
    schema: str = "tiny"
    batch_rows: int = 1 << 20
    target_splits: int = 1
    retry_policy: str = "none"
    query_retries: int = 2
    task_retries: int = 3
    # per-query memory budget (None = unlimited); exceeding it triggers
    # revocation/spill, then ExceededMemoryLimitError
    memory_pool_bytes: Optional[int] = None
    hash_partition_count: int = 4
    enable_dynamic_filtering: bool = True
    broadcast_join_threshold: int = 1_000_000

    def set_property(self, name: str, value) -> None:
        """SET SESSION entry point — validated through the typed
        registry (config.SYSTEM_PROPERTIES)."""
        from trino_tpu.config import bind_session

        bind_session(self, {name: value})


@dataclasses.dataclass
class MaterializedResult:
    """QueryAssertions' MaterializedResult analogue."""

    rows: List[list]
    column_names: List[str]
    column_types: List[T.DataType]

    def only_value(self):
        assert len(self.rows) == 1 and len(self.rows[0]) == 1, self.rows
        return self.rows[0][0]


class LocalQueryRunner:
    def __init__(self, session: Optional[Session] = None):
        self.session = session or Session()
        self.catalogs = CatalogManager()
        # SQL text -> (OutputNode, PhysicalPlan): re-executing a cached
        # query reuses every jitted device program (the reference's
        # expression/operator caches keyed on expression, §2.9)
        self._plan_cache: dict = {}
        from trino_tpu.runtime.events import EventListenerManager

        self.event_listeners = EventListenerManager()
        self._query_seq = 0

    def register_catalog(self, name: str, connector: Connector) -> None:
        self.catalogs.register(name, connector)

    # -- entry point --
    def execute(self, sql: str) -> MaterializedResult:
        stmt = parse(sql)
        if isinstance(stmt, ast.Query):
            return self._run_tracked(sql, stmt)
        if isinstance(stmt, ast.ExplainStatement):
            if stmt.analyze:
                return self._explain_analyze(stmt.query)
            plan = self._analyze(stmt.query)
            return MaterializedResult(
                [[explain_text(plan)]], ["Query Plan"], [T.VARCHAR]
            )
        if isinstance(stmt, ast.SetSession):
            # plan-shaping properties are part of the plan-cache key, so
            # no explicit invalidation is needed
            self.session.set_property(stmt.name, stmt.value)
            return MaterializedResult([[True]], ["result"], [T.BOOLEAN])
        if isinstance(stmt, ast.ShowSession):
            from trino_tpu.config import SYSTEM_PROPERTIES

            rows = []
            for meta in SYSTEM_PROPERTIES.all():
                current = getattr(self.session, meta.name, None)
                if meta.name == "memory_pool_bytes":
                    current = self.session.memory_pool_bytes or 0
                rows.append(
                    [meta.name, str(current), str(meta.default), meta.description]
                )
            return MaterializedResult(
                rows,
                ["Name", "Value", "Default", "Description"],
                [T.VARCHAR] * 4,
            )
        if isinstance(stmt, ast.ShowSchemas):
            cat = stmt.catalog or self.session.catalog
            conn = self.catalogs.get(cat)
            rows = [[s] for s in conn.metadata.list_schemas()]
            return MaterializedResult(rows, ["Schema"], [T.VARCHAR])
        if isinstance(stmt, ast.ShowTables):
            cat, schema = self.session.catalog, self.session.schema
            if stmt.schema:
                if len(stmt.schema) == 2:
                    cat, schema = stmt.schema
                else:
                    schema = stmt.schema[0]
            conn = self.catalogs.get(cat)
            rows = [[t] for t in conn.metadata.list_tables(schema)]
            return MaterializedResult(rows, ["Table"], [T.VARCHAR])
        if isinstance(stmt, ast.ShowColumns):
            parts = stmt.table
            cat, schema = self.session.catalog, self.session.schema
            table = parts[-1]
            if len(parts) == 2:
                schema = parts[0]
            elif len(parts) == 3:
                cat, schema = parts[0], parts[1]
            conn, handle = self.catalogs.resolve_table(cat, schema, table)
            meta = conn.metadata.get_table_metadata(handle)
            rows = [[c.name, str(c.type)] for c in meta.columns]
            return MaterializedResult(rows, ["Column", "Type"], [T.VARCHAR, T.VARCHAR])
        raise AnalysisError(f"cannot execute {type(stmt).__name__}")

    def _analyze(self, q: ast.Query) -> OutputNode:
        analyzer = Analyzer(self.catalogs, self.session.catalog, self.session.schema)
        return analyzer.plan(q)

    def _run_tracked(self, sql: str, stmt: ast.Query) -> MaterializedResult:
        """Query lifecycle: span tree + event listener dispatch around
        the actual execution (SqlQueryExecution's tracing shape)."""
        import time as _time

        from trino_tpu.runtime.events import (
            QueryCompletedEvent,
            QueryCreatedEvent,
        )
        from trino_tpu.utils.tracing import TRACER

        self._query_seq += 1
        query_id = f"local-{self._query_seq}"
        t0 = _time.monotonic()
        self.event_listeners.query_created(
            QueryCreatedEvent(query_id, sql, _time.time())
        )
        try:
            with TRACER.span("query", query_id=query_id):
                result = self._execute_query(stmt, sql_key=sql)
        except BaseException as e:
            self.event_listeners.query_completed(
                QueryCompletedEvent(
                    query_id, sql, "failed", _time.monotonic() - t0,
                    failure=repr(e),
                )
            )
            raise
        self.event_listeners.query_completed(
            QueryCompletedEvent(
                query_id, sql, "finished", _time.monotonic() - t0,
                rows=len(result.rows),
            )
        )
        return result

    def _plan(self, q: ast.Query, sql_key: Optional[str]):
        from trino_tpu.utils.tracing import TRACER

        # cache key includes the plan-shaping session properties, so
        # set_property takes effect however it was invoked
        cache_key = None
        if sql_key is not None:
            cache_key = (
                sql_key,
                self.session.batch_rows,
                self.session.target_splits,
                self.session.enable_dynamic_filtering,
            )
        cached = self._plan_cache.get(cache_key) if cache_key else None
        if cached is not None:
            return cached
        with TRACER.span("analyze"):
            output = self._analyze(q)
        with TRACER.span("plan"):
            planner = LocalPlanner(
                self.catalogs,
                batch_rows=self.session.batch_rows,
                target_splits=self.session.target_splits,
                dynamic_filtering=self.session.enable_dynamic_filtering,
            )
            physical = planner.plan(output)
        if cache_key:
            self._plan_cache[cache_key] = (output, physical)
        return output, physical

    def _execution_ctx(self) -> dict:
        ctx: dict = {}
        if self.session.memory_pool_bytes is not None:
            from trino_tpu.runtime.memory import MemoryPool

            ctx["memory_pool"] = MemoryPool(self.session.memory_pool_bytes)
        return ctx

    def _execute_query(self, q: ast.Query, sql_key: Optional[str] = None) -> MaterializedResult:
        from trino_tpu.utils.tracing import TRACER

        output, physical = self._plan(q, sql_key)
        pipelines, chain = physical.instantiate(self._execution_ctx())
        sink = CollectorSink()
        chain.append(sink)
        with TRACER.span("execute"):
            for p in pipelines:
                Driver(p).run()
            Driver(Pipeline(chain)).run()
        return MaterializedResult(
            sink.rows(),
            list(output.names),
            [f.type for f in output.fields],
        )

    def _explain_analyze(self, q: ast.Query) -> MaterializedResult:
        """EXPLAIN ANALYZE: run with instrumented operators, render plan
        + per-operator stats (ExplainAnalyzeOperator analogue)."""
        from trino_tpu.exec.stats import instrument, render_stats

        output, physical = self._plan(q, sql_key=None)
        pipelines, chain = physical.instantiate(self._execution_ctx())
        sink = CollectorSink()
        chain.append(sink)
        groups = []
        wrapped_pipelines = []
        for p in pipelines:
            ops, stats = instrument(p.operators)
            groups.append(stats)
            wrapped_pipelines.append(Pipeline(ops))
        main_ops, main_stats = instrument(chain)
        groups.append(main_stats)
        for p in wrapped_pipelines:
            Driver(p).run()
        Driver(Pipeline(main_ops)).run()
        text = explain_text(output) + "\n\n" + render_stats(groups)
        return MaterializedResult([[text]], ["Query Plan"], [T.VARCHAR])
