"""In-process query engine: SQL text in, rows out.

Analogue of Trino's LocalQueryRunner (main/testing/LocalQueryRunner.java:264
— plan and execute SQL fully in-process with real operators, SURVEY.md
§4.2) plus the session/catalog surface of Session + MetadataManager.
The distributed runner (coordinator/worker split over fragments) layers
on top of the same plans.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from trino_tpu import types as T
from trino_tpu.connectors.spi import CatalogManager, Connector
from trino_tpu.exec import CollectorSink, Driver, Pipeline
from trino_tpu.sql import ast
from trino_tpu.sql.analyzer import AnalysisError, Analyzer
from trino_tpu.sql.local_planner import LocalPlanner
from trino_tpu.sql.parser import parse
from trino_tpu.sql.plan import OutputNode, explain_text


@dataclasses.dataclass
class Session:
    """Per-query context (main/Session.java analogue; properties grow
    with the session-property system). retry_policy mirrors Trino's
    `retry_policy` session property: "none" (pipelined), "query"
    (whole-query retry inside the pipelined scheduler,
    PipelinedQueryScheduler.scheduleRetryWithDelay:394) or "task"
    (FTE over spooled exchange, SURVEY.md §3.5)."""

    catalog: str = "tpch"
    schema: str = "tiny"
    user: str = "user"
    # session time zone (Session.java getTimeZoneKey): fixes literal
    # parsing, timestamp<->tstz casts, now()/current_date
    timezone: str = "UTC"
    batch_rows: int = 1 << 20
    target_splits: int = 1
    retry_policy: str = "none"
    query_retry_count: int = 2
    task_retries: int = 3
    # per-query memory budget (None = unlimited); exceeding it triggers
    # revocation/spill, then ExceededMemoryLimitError
    memory_pool_bytes: Optional[int] = None
    hash_partition_count: int = 4
    enable_dynamic_filtering: bool = True
    broadcast_join_threshold: int = 1_000_000
    # distributed data plane: run mesh-colocated fragments as ONE
    # shard_map program with all_to_all/all_gather exchanges over ICI
    # (parallel/mesh_plan.py); ineligible plans and cross-host/FTE
    # topologies fall back to the HTTP page exchange
    mesh_execution: bool = True
    # rows per mesh chunk-step per shard: >0 splits the driver scan into
    # ceil(rows/chunk) jit steps with host preemption checks at every
    # chunk boundary; 0 compiles the plan as one program
    mesh_chunk_rows: int = 0
    # optimizer (sql/optimizer.py): the iterative rule pipeline and the
    # cost-based join reorderer (JOIN_REORDERING_STRATEGY analogue)
    enable_optimizer: bool = True
    join_reordering_strategy: str = "automatic"
    # connector scan pushdown (sql/optimizer.py PushPredicateIntoTableScan
    # / PushProjectionIntoTableScan via the apply_filter/apply_projection
    # SPI hooks)
    enable_pushdown: bool = True
    # FTE straggler mitigation: duplicate slow tasks, first wins
    # (retry-policy=TASK speculative execution). A task speculates once
    # it runs `speculation_quantile`x beyond the stage's median
    # committed-attempt wall time AND a spare schedulable worker exists.
    speculation_enabled: bool = True
    speculation_quantile: float = 2.0
    # intra-task pipeline parallelism (LocalExchange): parallel build
    # pipelines + host IO overlapped with device compute; 1 = off
    task_concurrency: int = 2
    # cluster resiliency (PR 2): per-destination transient-error budget
    # for inter-node requests (runtime/error_tracker.py), circuit
    # breaker graylisting thresholds (runtime/discovery.py), and the
    # last-resort low-memory killer (runtime/memory.py)
    request_max_error_duration_s: float = 30.0
    node_breaker_threshold: int = 3
    node_breaker_cooldown_s: float = 1.0
    low_memory_killer_enabled: bool = True
    # deadline hierarchy (PR 4, runtime/query_tracker.py): per-query
    # time budgets (0 = unlimited). Breaches are typed NON-RETRYABLE
    # errors (EXCEEDED_TIME_LIMIT / EXCEEDED_CPU_LIMIT) — the budget is
    # a property of the query, so neither QUERY retry nor FTE task
    # retry may resubmit past one
    query_max_planning_time_s: float = 0.0
    query_max_execution_time_s: float = 0.0
    query_max_run_time_s: float = 0.0
    query_max_cpu_time_s: float = 0.0
    # client-abandonment reaping (CoordinatorServer): a query whose
    # results page went unpolled this long is cancelled and its
    # resource-group slot + memory reservation released
    client_timeout_s: float = 300.0
    # worker stuck-task watchdog: interrupt a task making no batch
    # progress for this long (RETRYABLE, unlike deadline kills — a hung
    # split may succeed on another worker); 0 disables
    stuck_task_interrupt_s: float = 0.0
    # FTE speculation duration estimate: quantile of committed attempt
    # wall times per fragment (the reference's p75-based model)
    speculation_percentile: float = 0.75
    # plan sanity checking (sql/validate.py, PlanSanityChecker
    # analogue): "off" | "passes" (after each optimizer pass and after
    # fragmentation) | "rules" (also after every rule application +
    # plan-determinism double-planning — debug mode)
    plan_validation: str = "passes"
    # EXPLAIN (ANALYZE) warns when the shape census predicts more
    # distinct XLA lowerings than this per plan/fragment
    compile_churn_warn_threshold: int = 32
    # shape stabilization (compile/shapes.py): pad scan chunks to the
    # capacity class of their pre-pruning span so pushdown/dynamic-
    # filter pruning and FTE retries re-land on census-predicted
    # lowerings instead of minting data-dependent ones
    shape_stabilization: bool = True
    # geometric ratio between capacity-ladder rungs (power of two);
    # 2 = the native bucket_capacity grid, larger = fewer classes
    capacity_ladder_base: int = 2
    # census-driven AOT warmup (compile/warmup.py): "off" | "background"
    # (precompile predicted lowerings while the query runs) | "block"
    # (wait for warmup before execution — deterministic cold starts)
    warmup_mode: str = "off"
    # aggressive watchdog threshold once a task's predicted shape
    # classes are all warm (warmup/cache hits or a prior completed
    # run); 0 falls back to stuck_task_interrupt_s
    stuck_task_interrupt_warm_s: float = 0.0
    # query tracing (runtime/tracing.py): "on" records the full span
    # tree (phases/stages/task attempts/operators; worker spans grafted
    # into the coordinator's) for GET /v1/query/{id}/trace
    query_trace: str = "off"
    # serving tier (trino_tpu/serving/): plan-cache LRU bound,
    # micro-batch coalescing window (0 = batching off) + per-flush cap,
    # and the admission lanes' queue depths / shed Retry-After hint
    plan_cache_entries: int = 256
    micro_batch_window_ms: float = 0.0
    micro_batch_max: int = 16
    admission_fast_depth: int = 64
    admission_general_depth: int = 256
    admission_retry_after_s: float = 1.0
    # resident state tier (trino_tpu/resident/): tables whose point
    # lookups serve from pinned device-resident hash tables, the
    # device-memory pin budget (0 disables pinning), and the delta-side
    # capacity before background compaction folds it into the base
    resident_tables: str = ""
    resident_pin_budget_mb: int = 64
    resident_delta_max_rows: int = 4096
    # adaptive execution tier (trino_tpu/adaptive/): mid-query
    # re-planning from observed barrier stats, the divergence ratio
    # that triggers it, and shared-subtree (NOT IN / CTE) spooling
    adaptive_execution: bool = False
    adaptive_replan_threshold: float = 4.0
    shared_subtree_materialization: bool = False
    # skew-aware join plane (ISSUE 16): heavy-hitter classification at
    # build-side barriers, salted repartition on the mesh plane, the
    # DHHJ spill-mode re-plan floor, and the MXU matmul join-project
    # kernel with its profitability threshold
    skewed_join_salting: bool = False
    skew_hot_key_threshold: float = 0.2
    skew_spill_min_rows: int = 1 << 18
    mxu_join_enabled: bool = False
    mxu_join_min_work: float = 16.0
    # recovery tier (trino_tpu/recovery/): checkpoint the mesh step
    # loop's carries every N chunk boundaries (0 = off) so mesh faults
    # resume from the last checkpoint; bound in-run resume attempts;
    # tee completed fragment outputs into the subtree spool so QUERY
    # retry substitutes finished stages instead of recomputing them
    mesh_checkpoint_interval_chunks: int = 0
    mesh_resume_attempts: int = 2
    recovery_spool_stages: bool = False
    # replicated serving meshes (trino_tpu/runtime/replicas.py): carve
    # the device set into N identical sub-meshes, each running the same
    # prelude/step/flush programs; the coordinator load-balances across
    # healthy replicas and, with failover on, re-places an in-flight
    # chunked query onto a sibling when its replica dies or drains
    # (resuming from the host-portable checkpoint). Breaker thresholds
    # mirror the worker graylist (node_breaker_*), per replica.
    mesh_replicas: int = 1
    replica_failover_enabled: bool = True
    replica_breaker_threshold: int = 3
    replica_breaker_cooldown_s: float = 1.0
    # preemptive multi-tenancy (runtime/scheduler.py): chunk-granular
    # weighted-fair run queue per mesh with a fast lane for point
    # lookups; a fast arrival parks the running analytic (carries
    # snapshot to the host checkpoint store within park_max_bytes,
    # resume from chunk k warm); drain failover may split the
    # unstarted chunk range across siblings (work stealing)
    mesh_scheduler: bool = True
    preemption_enabled: bool = True
    park_max_bytes: int = 256 << 20
    mesh_scheduler_weights: str = ""
    mesh_scheduler_min_slice_chunks: int = 1
    mesh_scheduler_group: str = ""
    mesh_steal_enabled: bool = True
    # multi-host replica fabric (runtime/fabric.py): park budgets are
    # apportioned across resource groups by scheduler weight out of
    # mesh_park_max_bytes (0 = unscoped, fall back to park_max_bytes);
    # fabric_peers names sibling coordinators whose checkpoint stores
    # receive async pushes at checkpoint boundaries and serve pulls at
    # failover, with fabric_max_error_duration_s bounding the retry
    # budget per peer request
    mesh_park_max_bytes: int = 0
    fabric_peers: str = ""
    fabric_queue_depth: int = 8
    fabric_max_error_duration_s: float = 5.0

    def set_property(self, name: str, value) -> None:
        """SET SESSION entry point — validated through the typed
        registry (config.SYSTEM_PROPERTIES)."""
        from trino_tpu.config import bind_session

        bind_session(self, {name: value})


@dataclasses.dataclass
class MaterializedResult:
    """QueryAssertions' MaterializedResult analogue."""

    rows: List[list]
    column_names: List[str]
    column_types: List[T.DataType]
    # transaction protocol surface (StatementClientV1's
    # X-Trino-Started-Transaction-Id / Clear-Transaction-Id headers)
    started_transaction_id: Optional[str] = None
    cleared_transaction: bool = False
    # prepared-statement protocol surface (X-Trino-Added-Prepare /
    # X-Trino-Deallocated-Prepare response headers)
    added_prepare: Optional[tuple] = None
    deallocated_prepare: Optional[str] = None
    # which data plane executed the query: "local" (single-process),
    # "mesh" (ICI collectives), "http" (page exchange), "fte" (spooled).
    # Surfaces the silent mesh fallback (VERDICT r2 weak #4).
    data_plane: str = "local"

    def only_value(self):
        assert len(self.rows) == 1 and len(self.rows[0]) == 1, self.rows
        return self.rows[0][0]


def _raise_deferred_checks(ctx: dict) -> None:
    """Assertions deferred to the end-of-query sync point (the results
    are already materialized, so these bools are cheap)."""
    for flag, msg in ctx.get("deferred_checks", ()):
        if bool(flag):
            raise RuntimeError(msg)


class LocalQueryRunner:
    def __init__(
        self,
        session: Optional[Session] = None,
        access_control=None,
    ):
        from trino_tpu.security import AllowAllAccessControl, Identity
        from trino_tpu.transaction import TransactionManager

        self.session = session or Session()
        self.catalogs = CatalogManager()
        # PREPARE store: name -> (ast statement, formatted text); the
        # HTTP protocol's prepared-statement headers mirror this
        self._prepared: Dict[str, tuple] = {}
        self._request_prepared: Optional[Dict[str, str]] = None
        # canonical text -> (OutputNode, PhysicalPlan): re-executing a
        # cached query reuses every jitted device program (the
        # reference's expression/operator caches keyed on expression,
        # §2.9); serving/plan_cache.py owns keying/LRU/counters
        from trino_tpu.serving.plan_cache import PlanCache

        self._plan_cache = PlanCache(
            max_entries=getattr(self.session, "plan_cache_entries", 256)
        )
        # dtype vector of the current EXECUTE's bound parameters (part
        # of the plan-cache key; set around the re-dispatch). Thread-
        # local: the HTTP server runs concurrent statements on one
        # runner, and one thread's EXECUTE must not perturb another
        # thread's cache key.
        import threading as _threading

        self._bound_dtypes_tls = _threading.local()
        from trino_tpu.runtime.events import EventListenerManager

        self.event_listeners = EventListenerManager()
        self.event_listeners.register_metrics()
        # per-query compile attribution + the xla_compile_duration_s
        # histogram need the jax.monitoring hook from process start,
        # not just from the first EXPLAIN ANALYZE
        from trino_tpu.runtime.metrics import install_xla_compile_listener

        install_xla_compile_listener()
        self._query_seq = 0
        # observability surfaces filled per query: the execution ctx's
        # memory pool (peak watermark) and the last completed span tree
        self._last_pool = None
        self._last_trace: Optional[tuple] = None
        self.access_control = access_control or AllowAllAccessControl()
        self.transactions = TransactionManager(self.catalogs)
        self._current_txn: Optional[str] = None
        import threading as _threading

        # per-request identity override (HTTP front passes the
        # authenticated principal; the runner is shared across threads)
        self._identity_override = _threading.local()
        # per-statement active transaction (explicit protocol threading)
        self._stmt_txn = _threading.local()

    @property
    def identity(self):
        from trino_tpu.security import Identity

        override = getattr(self._identity_override, "value", None)
        return override or Identity(self.session.user)

    def _check_scans(self, plan) -> None:
        """AccessControl over every table the plan reads (the analyzer
        already resolved views/CTEs away, so ScanNodes are the full
        read set — StatementAnalyzer's table references)."""
        from trino_tpu.sql.plan import ScanNode

        def walk(node):
            if isinstance(node, ScanNode):
                h = node.handle
                self.access_control.check_can_select(
                    self.identity, h.catalog, h.schema, h.table,
                    node.columns,
                )
            for c in node.children():
                walk(c)

        walk(plan)

    def register_catalog(self, name: str, connector: Connector) -> None:
        self.catalogs.register(name, connector)

    # -- entry point --
    def execute(
        self, sql: str, identity=None, transaction_id: Optional[str] = None,
        prepared: Optional[Dict[str, str]] = None,
    ) -> MaterializedResult:
        """`identity` overrides the session user for this statement (the
        HTTP front passes the authenticated principal).

        `transaction_id` selects EXPLICIT transaction threading — the
        protocol model, where each client connection carries its own
        transaction id (X-Trino-Transaction-Id) and the shared runner
        holds no cross-client state. Pass the sentinel "NONE" for an
        autocommit statement in explicit mode. When None (embedded
        use), the runner's own session transaction applies."""
        stmt = parse(sql)
        explicit = transaction_id is not None
        active = (
            None if transaction_id in (None, "NONE") else transaction_id
        )
        if not explicit:
            active = self._current_txn
        if identity is not None:
            self._identity_override.value = identity
        self._stmt_txn.value = active
        self._request_prepared = prepared
        try:
            return self._dispatch(stmt, sql, active, explicit)
        finally:
            self._stmt_txn.value = None
            self._request_prepared = None
            if identity is not None:
                self._identity_override.value = None

    def _active_txn(self) -> Optional[str]:
        return getattr(self._stmt_txn, "value", None)

    def _check_writable(self) -> None:
        txn = self._active_txn()
        if txn is not None and self.transactions.is_read_only(txn):
            from trino_tpu.transaction import TransactionError

            raise TransactionError(
                "READ_ONLY_VIOLATION: cannot write in a read-only transaction"
            )

    def _dispatch(
        self, stmt, sql: str, active: Optional[str], explicit: bool
    ) -> MaterializedResult:
        from trino_tpu.transaction import TransactionError

        self.access_control.check_can_execute_query(self.identity)
        if isinstance(stmt, ast.Prepare):
            # PREPARE name FROM stmt (tree/Prepare.java:25; the protocol
            # threads these via X-Trino-Prepared-Statement headers —
            # runtime/server mirrors this session store per request)
            from trino_tpu.sql.formatter import format_statement

            try:
                text = format_statement(stmt.statement)
            except Exception:
                text = stmt.sql or ""
            self._prepared[stmt.name] = (stmt.statement, text)
            res = MaterializedResult([[True]], ["result"], [T.BOOLEAN])
            res.added_prepare = (stmt.name, text)
            return res
        if isinstance(stmt, ast.ExecuteStmt):
            # request-carried statements (X-Trino-Prepared-Statement)
            # take precedence: they are CLIENT-session state, while the
            # instance store is shared across every caller
            hit = None
            if self._request_prepared:
                text = self._request_prepared.get(stmt.name)
                if text is not None:
                    hit = (parse(text), text)
            if hit is None:
                hit = self._prepared.get(stmt.name)
            if hit is None:
                raise ValueError(
                    f"Prepared statement not found: {stmt.name}"
                )
            # typed binding check BEFORE substitution: arity and dtype
            # mismatches fail here with position/expected/got instead of
            # surfacing as an analyzer error deep inside the spliced
            # statement (serving/params.py)
            from trino_tpu.serving.params import check_parameters

            dtypes = check_parameters(
                hit[0], stmt.parameters, self.catalogs,
                self.session.catalog, self.session.schema,
            )
            body = ast.substitute_parameters(hit[0], stmt.parameters)
            # the plan-cache key canonicalizes the BOUND statement, so
            # distinct bindings plan separately; the dtype vector rides
            # along as its own key component (serving/plan_cache.py)
            prior = getattr(self._bound_dtypes_tls, "value", None)
            self._bound_dtypes_tls.value = tuple(dtypes)
            try:
                return self._dispatch(body, sql, active, explicit)
            finally:
                self._bound_dtypes_tls.value = prior
        if isinstance(stmt, ast.Deallocate):
            if stmt.name not in self._prepared:
                raise ValueError(
                    f"Prepared statement not found: {stmt.name}"
                )
            del self._prepared[stmt.name]
            res = MaterializedResult([[True]], ["result"], [T.BOOLEAN])
            res.deallocated_prepare = stmt.name
            return res
        if isinstance(stmt, ast.StartTransaction):
            if active is not None:
                raise TransactionError("transaction already in progress")
            new_txn = self.transactions.begin(stmt.read_only)
            if not explicit:
                self._current_txn = new_txn
            return MaterializedResult(
                [[True]], ["result"], [T.BOOLEAN],
                started_transaction_id=new_txn,
            )
        if isinstance(stmt, ast.Commit):
            if active is None:
                raise TransactionError("NOT_IN_TRANSACTION: no transaction in progress")
            try:
                self.transactions.commit(active)
            finally:
                # a failed commit still ends the transaction (the
                # reference's semantics) — never wedge the session
                if not explicit:
                    self._current_txn = None
                self._invalidate_plans()
            return MaterializedResult(
                [[True]], ["result"], [T.BOOLEAN], cleared_transaction=True
            )
        if isinstance(stmt, ast.Rollback):
            if active is None:
                raise TransactionError("NOT_IN_TRANSACTION: no transaction in progress")
            try:
                self.transactions.rollback(active)
            finally:
                if not explicit:
                    self._current_txn = None
            return MaterializedResult(
                [[True]], ["result"], [T.BOOLEAN], cleared_transaction=True
            )
        if isinstance(stmt, ast.Query):
            return self._run_tracked(sql, stmt)
        if isinstance(stmt, ast.ExplainStatement):
            if stmt.analyze:
                return self._explain_analyze(stmt.query)
            plan = self._analyze(stmt.query)
            return MaterializedResult(
                [[explain_text(plan)]], ["Query Plan"], [T.VARCHAR]
            )
        if isinstance(stmt, ast.CreateTable):
            from trino_tpu.connectors.spi import ColumnMetadata
            from trino_tpu.sql.analyzer import resolve_type

            cat, conn, schema, table = self._resolve_target(stmt.table)
            self.access_control.check_can_create_table(
                self.identity, conn.name, schema, table
            )
            self._check_writable()
            cols = [
                ColumnMetadata(n, resolve_type(t)) for n, t in stmt.columns
            ]
            conn.metadata.create_table(schema, table, cols)
            self._invalidate_plans(table=(cat, schema, table))
            return MaterializedResult([[True]], ["result"], [T.BOOLEAN])
        if isinstance(stmt, ast.CreateTableAs):
            return self._execute_ctas(stmt)
        if isinstance(stmt, ast.Insert):
            return self._execute_insert(stmt.table, stmt.columns, stmt.query)
        if isinstance(stmt, ast.Delete):
            return self._execute_rewrite_dml(stmt.table, stmt.where, None)
        if isinstance(stmt, ast.Merge):
            return self._execute_merge(stmt)
        if isinstance(stmt, ast.Update):
            names = [c for c, _ in stmt.assignments]
            if len(set(names)) != len(names):
                raise AnalysisError("multiple assignments for the same column")
            return self._execute_rewrite_dml(
                stmt.table, stmt.where, dict(stmt.assignments)
            )
        if isinstance(stmt, ast.DropTable):
            cat, conn, schema, table = self._resolve_target(stmt.table)
            self.access_control.check_can_drop_table(
                self.identity, conn.name, schema, table
            )
            self._check_writable()
            handle = conn.metadata.get_table_handle(schema, table)
            if handle is None:
                raise AnalysisError(f"table {schema}.{table} does not exist")
            conn.metadata.drop_table(handle)
            self._invalidate_plans(table=(cat, schema, table))
            return MaterializedResult([[True]], ["result"], [T.BOOLEAN])
        if isinstance(stmt, ast.SetSession):
            self.access_control.check_can_set_session_property(
                self.identity, stmt.name
            )
            # plan-shaping properties are part of the plan-cache key, so
            # no explicit invalidation is needed
            self.session.set_property(stmt.name, stmt.value)
            return MaterializedResult([[True]], ["result"], [T.BOOLEAN])
        if isinstance(stmt, ast.ShowSession):
            from trino_tpu.config import SYSTEM_PROPERTIES

            rows = []
            for meta in SYSTEM_PROPERTIES.all():
                current = getattr(self.session, meta.name, None)
                if meta.name == "memory_pool_bytes":
                    current = self.session.memory_pool_bytes or 0
                rows.append(
                    [meta.name, str(current), str(meta.default), meta.description]
                )
            return MaterializedResult(
                rows,
                ["Name", "Value", "Default", "Description"],
                [T.VARCHAR] * 4,
            )
        if isinstance(stmt, ast.ShowFunctions):
            from trino_tpu.expr.registry import REGISTRY

            rows = []
            for m in REGISTRY.all():
                arity = (
                    str(m.min_arity)
                    if m.max_arity == m.min_arity
                    else f"{m.min_arity}..{m.max_arity or 'N'}"
                )
                # one row per callable name and per concrete overload —
                # aliases and per-type signatures are rows, the
                # reference's SHOW FUNCTIONS unit (ceiling, pow, dow;
                # abs listed once per numeric type)
                sigs = m.overloads or (m.returns,)
                for nm in (m.name, *m.aliases):
                    for sig in sigs:
                        rows.append(
                            [nm, sig, arity, m.category, m.description]
                        )
            rows.sort(key=lambda r: (r[3], r[0], r[1]))
            return MaterializedResult(
                rows,
                ["Function", "Return Type", "Arity", "Function Type",
                 "Description"],
                [T.VARCHAR] * 5,
            )
        if isinstance(stmt, ast.ShowSchemas):
            cat = stmt.catalog or self.session.catalog
            conn = self.catalogs.get(cat)
            rows = [[s] for s in conn.metadata.list_schemas()]
            return MaterializedResult(rows, ["Schema"], [T.VARCHAR])
        if isinstance(stmt, ast.ShowTables):
            cat, schema = self.session.catalog, self.session.schema
            if stmt.schema:
                if len(stmt.schema) == 2:
                    cat, schema = stmt.schema
                else:
                    schema = stmt.schema[0]
            conn = self.catalogs.get(cat)
            rows = [[t] for t in conn.metadata.list_tables(schema)]
            return MaterializedResult(rows, ["Table"], [T.VARCHAR])
        if isinstance(stmt, ast.ShowColumns):
            parts = stmt.table
            cat, schema = self.session.catalog, self.session.schema
            table = parts[-1]
            if len(parts) == 2:
                schema = parts[0]
            elif len(parts) == 3:
                cat, schema = parts[0], parts[1]
            conn, handle = self.catalogs.resolve_table(cat, schema, table)
            meta = conn.metadata.get_table_metadata(handle)
            rows = [[c.name, str(c.type)] for c in meta.columns]
            return MaterializedResult(rows, ["Column", "Type"], [T.VARCHAR, T.VARCHAR])
        raise AnalysisError(f"cannot execute {type(stmt).__name__}")

    def _analyze(self, q: ast.Query) -> OutputNode:
        from trino_tpu.sql.analyzer import (
            set_session_info,
            set_session_zone,
        )
        from trino_tpu.sql.optimizer import (
            canonicalize_tstz_keys,
            optimize,
        )

        set_session_zone(self.session.timezone)
        set_session_info(
            self.session.catalog, self.session.schema,
            self.identity.user,
        )
        analyzer = Analyzer(self.catalogs, self.session.catalog, self.session.schema)
        root = optimize(analyzer.plan(q), self.catalogs, self.session)
        # correctness pass: runs regardless of enable_optimizer
        root = canonicalize_tstz_keys(root)
        mode = getattr(self.session, "plan_validation", "passes")
        if mode != "off":
            from trino_tpu.sql.validate import validate_logical

            validate_logical(root, stage="canonicalize_tstz_keys")
        if mode == "rules":
            # PlanDeterminismChecker: replanning the same AST must yield
            # byte-identical EXPLAIN text (fresh analyzer per run — the
            # plan cache would otherwise mask nondeterminism)
            from trino_tpu.sql.validate import check_plan_determinism

            def plan_once():
                a = Analyzer(
                    self.catalogs, self.session.catalog, self.session.schema
                )
                return canonicalize_tstz_keys(
                    optimize(a.plan(q), self.catalogs, self.session)
                )

            check_plan_determinism(plan_once)
        return root

    def _invalidate_plans(self, table=None, appended: bool = False,
                          tap=None) -> None:
        """Cached physical plans capture split lists (data snapshots) at
        plan time, so any write/DDL invalidates them — the analogue of
        the reference re-planning every query against current metadata.

        When the write can name its target (`table` = (catalog, schema,
        table)), invalidation is table-granular: only plans reading the
        written table drop, the table's generation counter bumps (the
        resident-tier invalidation protocol), and pinned resident state
        over the table is evicted — or, for an INSERT whose rows a
        `DeltaTap` captured (`appended`/`tap`), re-keyed onto the delta
        side so the pin stays warm. Writes that cannot name a table
        (COMMIT) stay wholesale."""
        from trino_tpu.resident import GENERATIONS, RESIDENT
        from trino_tpu.resident import fastlane as _fastlane
        from trino_tpu.resident.manager import table_key

        from trino_tpu.recovery import CHECKPOINTS

        if table is None:
            self._plan_cache.invalidate()
            GENERATIONS.bump_all()
            RESIDENT.evict_all()
            CHECKPOINTS.clear()
            return
        tkey = table_key(*table)
        self._plan_cache.invalidate_tables([tkey])
        GENERATIONS.bump(tkey)
        _fastlane.table_written(*tkey, appended=appended, tap=tap)
        # mesh checkpoints over the written table are stale by
        # construction: the generation guard already makes them
        # unreachable — reclaim their host memory eagerly
        CHECKPOINTS.invalidate_table(*tkey)

    # -- DML (BeginTableWrite/TableWriter/TableFinish path) --
    def _resolve_target(self, parts):
        # returns the REGISTERED catalog name alongside the connector:
        # conn.name is the connector type ("file"), which need not match
        # the registration name ("files") that plan/resident table keys
        # are built from on the read side
        cat, schema = self.session.catalog, self.session.schema
        table = parts[-1]
        if len(parts) == 2:
            schema = parts[0]
        elif len(parts) == 3:
            cat, schema = parts[0], parts[1]
        return cat, self.catalogs.get(cat), schema, table

    def _execute_ctas(self, stmt: ast.CreateTableAs) -> MaterializedResult:
        from trino_tpu.connectors.spi import ColumnMetadata

        output = self._analyze(stmt.query)
        self._check_scans(output)
        cat, conn, schema, table = self._resolve_target(stmt.table)
        self.access_control.check_can_create_table(
            self.identity, conn.name, schema, table
        )
        self._check_writable()  # before the table is created
        cols = [
            ColumnMetadata(n or f"_col{i}", f.type)
            for i, (n, f) in enumerate(zip(output.names, output.fields))
        ]
        conn.metadata.create_table(schema, table, cols)
        return self._write_into(
            cat, conn, schema, table, output, list(output.names)
        )

    def _execute_insert(self, parts, columns, query: ast.Query) -> MaterializedResult:
        cat, conn, schema, table = self._resolve_target(parts)
        self.access_control.check_can_insert(
            self.identity, conn.name, schema, table
        )
        output = self._analyze(query)
        self._check_scans(output)
        return self._write_into(
            cat, conn, schema, table, output,
            list(columns) if columns else None,
        )

    def _execute_rewrite_dml(
        self, parts, where, assignments: Optional[dict]
    ) -> MaterializedResult:
        """DELETE (assignments=None) / UPDATE as a read-rewrite: scan
        the surviving/updated rows into device batches, truncate, and
        re-append — the memory-connector analogue of the reference's
        row-level delete/update pushdown. Affected-row count comes from
        a matched-rows count pass."""
        from trino_tpu.transaction import TransactionError

        cat, conn, schema, table = self._resolve_target(parts)
        check = (
            self.access_control.check_can_delete
            if assignments is None
            else self.access_control.check_can_update
        )
        check(self.identity, conn.name, schema, table)
        self._check_writable()
        if self._active_txn() is not None:
            raise TransactionError(
                "DELETE/UPDATE inside an explicit transaction is not supported"
            )
        handle = conn.metadata.get_table_handle(schema, table)
        if handle is None:
            raise AnalysisError(f"table {schema}.{table} does not exist")
        meta = conn.metadata.get_table_metadata(handle)
        if assignments is not None:
            known = {c.name for c in meta.columns}
            for col in assignments:
                if col not in known:
                    raise AnalysisError(f"unknown column {col} in UPDATE")
        rel = ast.TableRef(parts)
        matched = (
            where
            if where is not None
            else ast.BooleanLiteral(True)
        )
        count_q = ast.Query(
            ast.QuerySpec(
                (ast.SelectItem(ast.FunctionCall("count", (ast.Star(),))),),
                from_=rel,
                where=where,
            )
        )
        affected = self._execute_query(count_q).only_value()

        if assignments is None:
            # keep rows where the predicate is NOT TRUE
            keep = (
                ast.UnaryOp(
                    "not",
                    ast.FunctionCall(
                        "coalesce", (where, ast.BooleanLiteral(False))
                    ),
                )
                if where is not None
                else None
            )
            if keep is None:  # unconditional DELETE = truncate
                conn.metadata.truncate_table(handle)
                self._invalidate_plans(table=(cat, schema, table))
                return MaterializedResult([[affected]], ["rows"], [T.BIGINT])
            select = tuple(
                ast.SelectItem(ast.Identifier((c.name,))) for c in meta.columns
            )
            rewrite_q = ast.Query(
                ast.QuerySpec(select, from_=rel, where=keep)
            )
        else:
            # per column: CASE WHEN pred THEN new ELSE old END
            items = []
            for c in meta.columns:
                old = ast.Identifier((c.name,))
                if c.name in assignments:
                    new = assignments[c.name]
                    e = (
                        ast.Case(
                            None,
                            (ast.WhenClause(matched, new),),
                            old,
                        )
                        if where is not None
                        else new
                    )
                else:
                    e = old
                items.append(ast.SelectItem(e, c.name))
            rewrite_q = ast.Query(ast.QuerySpec(tuple(items), from_=rel))

        self._replace_table_from_queries(cat, conn, handle, meta, [rewrite_q])
        return MaterializedResult([[affected]], ["rows"], [T.BIGINT])

    def _replace_table_from_queries(
        self, cat, conn, handle, meta, queries
    ) -> List[int]:
        """Materialize each rewrite query, coerce onto the table
        schema, and swap the combined batches in as the table's new
        contents (shared by DELETE/UPDATE/MERGE read-rewrites; MERGE
        runs survivors and inserts as separate queries so their string
        columns keep independent dictionaries). Returns the per-query
        materialized row counts (MERGE reads the insert count)."""
        from trino_tpu.expr import ir
        from trino_tpu.sql import plan as P

        batches = []
        counts = []
        for rewrite_q in queries:
            output = self._analyze(rewrite_q)
            # rewrite subqueries may scan other tables: same SELECT
            # access checks as any query
            self._check_scans(output)
            # coerce rewritten columns back onto the table schema
            # (UPDATE expressions may widen types), as the INSERT path
            exprs = []
            for i, col in enumerate(meta.columns):
                e: ir.Expr = ir.InputRef(i, output.fields[i].type)
                if output.fields[i].type != col.type:
                    e = ir.Cast(e, col.type)
                exprs.append(e)
            fields = tuple(P.Field(c.name, c.type) for c in meta.columns)
            node = P.ProjectNode(output.child, tuple(exprs), fields)
            planner = LocalPlanner(
                self.catalogs,
                batch_rows=self.session.batch_rows,
                target_splits=self.session.target_splits,
                dynamic_filtering=self.session.enable_dynamic_filtering,
            )
            physical = planner.plan(node)
            ctx = self._execution_ctx()
            pipelines, chain = physical.instantiate(ctx)
            sink = CollectorSink()
            chain.append(sink)
            for p in pipelines:
                Driver(p).run()
            Driver(Pipeline(chain)).run()
            _raise_deferred_checks(ctx)
            counts.append(sum(int(b.row_count()) for b in sink.batches))
            batches.extend(sink.batches)
        # commit the rewrite: connectors with replace_rows do it
        # atomically (stage-then-swap); the fallback truncate+append is
        # NOT crash-atomic
        replace = getattr(conn, "replace_rows", None)
        if replace is not None:
            replace(handle, batches)
        else:
            conn.metadata.truncate_table(handle)
            writer_sink = conn.page_sink(handle)
            for b in batches:
                writer_sink.append(b)
            writer_sink.finish()
        self._invalidate_plans(
            table=(cat, handle.schema, handle.table)
        )
        return counts

    def _execute_merge(self, stmt: ast.Merge) -> MaterializedResult:
        """MERGE as a read-rewrite over the existing query machinery
        (parser/sql/tree/Merge.java; the reference plans MERGE onto its
        row-change paradigm — here the whole statement compiles to ONE
        survivors-UNION-ALL-inserts query that becomes the table's new
        contents, the same strategy as DELETE/UPDATE):

        - survivors: target LEFT JOIN source; per column a CASE chain
          applies the FIRST matching WHEN MATCHED arm; rows whose first
          arm is DELETE drop.
        - inserts: source rows with NO target match (NOT EXISTS) and a
          matching WHEN NOT MATCHED arm.
        - a target row matching >1 source rows is an error (Trino's
          MERGE cardinality rule), checked with a row_number-keyed
          grouped count before the rewrite."""
        from trino_tpu.transaction import TransactionError

        cat, conn, schema, table = self._resolve_target(stmt.table)
        # each privilege gates only on the arms actually present
        # (Trino checks UPDATE/DELETE/INSERT per MERGE case kind)
        if any(c.action == "update" for c in stmt.clauses):
            self.access_control.check_can_update(
                self.identity, conn.name, schema, table
            )
        if any(not c.matched for c in stmt.clauses):
            self.access_control.check_can_insert(
                self.identity, conn.name, schema, table
            )
        if any(c.action == "delete" for c in stmt.clauses):
            self.access_control.check_can_delete(
                self.identity, conn.name, schema, table
            )
        self._check_writable()
        if self._active_txn() is not None:
            raise TransactionError(
                "MERGE inside an explicit transaction is not supported"
            )
        handle = conn.metadata.get_table_handle(schema, table)
        if handle is None:
            raise AnalysisError(f"table {schema}.{table} does not exist")
        meta = conn.metadata.get_table_metadata(handle)
        known = {c.name for c in meta.columns}
        for cl in stmt.clauses:
            set_names = [c for c, _ in cl.assignments]
            if len(set(set_names)) != len(set_names):
                raise AnalysisError(
                    "multiple assignments for the same column in MERGE"
                )
            for col in set_names:
                if col not in known:
                    raise AnalysisError(f"unknown column {col} in MERGE")
            if cl.action == "insert":
                cols = cl.insert_columns or tuple(
                    c.name for c in meta.columns
                )
                if len(cols) != len(cl.insert_values):
                    raise AnalysisError(
                        "MERGE INSERT column/value count mismatch"
                    )
                for col in cols:
                    if col not in known:
                        raise AnalysisError(
                            f"unknown column {col} in MERGE INSERT"
                        )

        t_alias = stmt.target_alias or table
        s_alias = getattr(stmt.source, "alias", None)
        if s_alias is None and isinstance(stmt.source, ast.TableRef):
            s_alias = stmt.source.name[-1]
        if s_alias is None:
            raise AnalysisError("MERGE source requires an alias")
        target_rel = ast.TableRef(stmt.table, alias=t_alias)
        true_lit = ast.BooleanLiteral(True)
        false_lit = ast.BooleanLiteral(False)

        def tcol(name: str) -> ast.Identifier:
            return ast.Identifier((t_alias, name))

        # cardinality rule: no target row may match more than one
        # source row (io.trino MERGE_TARGET_ROW_MULTIPLE_MATCHES)
        rid_target = ast.SubqueryRelation(
            ast.Query(ast.QuerySpec(
                (ast.SelectItem(ast.Star()),
                 ast.SelectItem(
                     ast.WindowCall("row_number", (), ast.WindowSpec()),
                     "__merge_rid",
                 )),
                from_=ast.TableRef(stmt.table),
            )),
            alias=t_alias,
        )
        dup_q = ast.Query(ast.QuerySpec(
            (ast.SelectItem(ast.FunctionCall("count", (ast.Star(),))),),
            from_=ast.SubqueryRelation(
                ast.Query(ast.QuerySpec(
                    (ast.SelectItem(tcol("__merge_rid")),),
                    from_=ast.Join(
                        "inner", rid_target, stmt.source, stmt.on
                    ),
                    group_by=(tcol("__merge_rid"),),
                    having=ast.BinaryOp(
                        "gt",
                        ast.FunctionCall("count", (ast.Star(),)),
                        ast.NumberLiteral("1"),
                    ),
                )),
                alias="__merge_dups",
            ),
        ))
        if (
            any(c.matched for c in stmt.clauses)
            and self._execute_query(dup_q).only_value() > 0
        ):
            raise RuntimeError(
                "One MERGE target table row matched more than one "
                "source row"
            )

        # matched flag rides the source side of the LEFT JOIN
        flagged_source = ast.SubqueryRelation(
            ast.Query(ast.QuerySpec(
                (ast.SelectItem(ast.Star()),
                 ast.SelectItem(true_lit, "__merge_m")),
                from_=stmt.source,
            )),
            alias=s_alias,
        )
        matched = ast.FunctionCall(
            "coalesce",
            (ast.Identifier((s_alias, "__merge_m")), false_lit),
        )
        m_clauses = [c for c in stmt.clauses if c.matched]
        nm_clauses = [c for c in stmt.clauses if not c.matched]

        # survivors: per column, the FIRST matching arm's value. With
        # no WHEN MATCHED arm the target is untouched — and must NOT
        # join (a LEFT JOIN would fan out on multiple source matches,
        # which insert-only MERGE legally allows)
        if not m_clauses:
            survivors = ast.QuerySpec(
                tuple(
                    ast.SelectItem(tcol(c.name), c.name)
                    for c in meta.columns
                ),
                from_=target_rel,
            )
        else:
            items = []
            for col in meta.columns:
                old = tcol(col.name)
                whens = []
                for cl in m_clauses:
                    cond = cl.condition or true_lit
                    val = dict(cl.assignments).get(col.name, old) \
                        if cl.action == "update" else old
                    whens.append(ast.WhenClause(
                        ast.BinaryOp("and", matched, cond), val
                    ))
                items.append(ast.SelectItem(
                    ast.Case(None, tuple(whens), old), col.name
                ))
            # a row drops iff matched AND its first applicable arm is
            # DELETE
            del_whens = [
                ast.WhenClause(
                    cl.condition or true_lit,
                    true_lit if cl.action == "delete" else false_lit,
                )
                for cl in m_clauses
            ]
            drop = ast.BinaryOp(
                "and", matched, ast.Case(None, tuple(del_whens), false_lit)
            )
            survivors = ast.QuerySpec(
                tuple(items),
                from_=ast.Join("left", target_rel, flagged_source, stmt.on),
                where=ast.UnaryOp("not", drop),
            )

        # affected rows: matched pairs whose first arm applies + inserts
        m_any = None
        for cl in m_clauses:
            c = cl.condition or true_lit
            m_any = c if m_any is None else ast.BinaryOp("or", m_any, c)
        updated = 0
        if m_clauses:
            updated = self._execute_query(ast.Query(ast.QuerySpec(
                (ast.SelectItem(ast.FunctionCall("count", (ast.Star(),))),),
                from_=ast.Join("inner", target_rel, stmt.source, stmt.on),
                where=m_any,
            ))).only_value()

        if nm_clauses:
            anti = ast.Exists(ast.Query(ast.QuerySpec(
                (ast.SelectItem(ast.NumberLiteral("1")),),
                from_=target_rel,
                where=stmt.on,
            )), negated=True)
            nm_any = None
            for cl in nm_clauses:
                c = cl.condition or true_lit
                nm_any = c if nm_any is None else ast.BinaryOp("or", nm_any, c)
            ins_items = []
            for col in meta.columns:
                whens = []
                for cl in nm_clauses:
                    cols = cl.insert_columns or tuple(
                        c.name for c in meta.columns
                    )
                    vmap = dict(zip(cols, cl.insert_values))
                    val = vmap.get(col.name, ast.NullLiteral())
                    whens.append(ast.WhenClause(
                        cl.condition or true_lit, val
                    ))
                ins_items.append(ast.SelectItem(
                    ast.Case(None, tuple(whens), ast.NullLiteral()),
                    col.name,
                ))
            ins_where = ast.BinaryOp("and", anti, nm_any)
            insert_spec = ast.QuerySpec(
                tuple(ins_items), from_=stmt.source, where=ins_where,
            )

        queries = [ast.Query(survivors)]
        if nm_clauses:
            queries.append(ast.Query(insert_spec))
        counts = self._replace_table_from_queries(
            cat, conn, handle, meta, queries
        )
        # the insert rewrite IS the anti-join — its materialized row
        # count is the inserted count (no third join execution)
        inserted = counts[1] if nm_clauses else 0
        return MaterializedResult(
            [[updated + inserted]], ["rows"], [T.BIGINT]
        )

    def _write_into(
        self, cat: str, conn, schema: str, table: str, output: OutputNode,
        insert_columns: Optional[List[str]],
    ) -> MaterializedResult:
        """Coerce the source onto the table schema and stream it into
        the connector page sink (TableWriterOperator)."""
        from trino_tpu.expr import ir
        from trino_tpu.exec.operators import TableWriterOperator
        from trino_tpu.sql import plan as P

        handle = conn.metadata.get_table_handle(schema, table)
        if handle is None:
            raise AnalysisError(f"table {schema}.{table} does not exist")
        meta = conn.metadata.get_table_metadata(handle)
        src_fields = output.fields
        if insert_columns is None:
            insert_columns = [c.name for c in meta.columns[: len(src_fields)]]
        if len(insert_columns) != len(src_fields):
            raise AnalysisError(
                f"INSERT has {len(src_fields)} columns but {len(insert_columns)} targets"
            )
        if len(set(insert_columns)) != len(insert_columns):
            raise AnalysisError("duplicate target column names in INSERT/CTAS")
        src_of = {name: i for i, name in enumerate(insert_columns)}
        exprs = []
        for col in meta.columns:
            i = src_of.get(col.name)
            if i is None:
                exprs.append(ir.Cast(ir.Literal(None, T.UNKNOWN), col.type))
                continue
            e: ir.Expr = ir.InputRef(i, src_fields[i].type)
            if src_fields[i].type != col.type:
                e = ir.Cast(e, col.type)
            exprs.append(e)
        fields = tuple(P.Field(c.name, c.type) for c in meta.columns)
        node = P.ProjectNode(output.child, tuple(exprs), fields)
        planner = LocalPlanner(
            self.catalogs,
            batch_rows=self.session.batch_rows,
            target_splits=self.session.target_splits,
            dynamic_filtering=self.session.enable_dynamic_filtering,
        )
        physical = planner.plan(node)
        ctx = self._execution_ctx()
        pipelines, chain = physical.instantiate(ctx)
        self._check_writable()
        active = self._active_txn()
        txn_handle = (
            self.transactions.join(active, conn.name, conn)
            if active is not None
            else None
        )
        if txn_handle is None and self.session.task_concurrency > 1:
            # autocommit bulk writes scale out with observed volume
            # (ScaledWriterSink); transactional writes keep ONE sink so
            # the commit stays a single handshake
            from trino_tpu.exec.operators import ScaledWriterSink

            sink_impl = ScaledWriterSink(
                lambda: conn.page_sink(handle),
                max_writers=self.session.task_concurrency,
            )
        else:
            sink_impl = conn.page_sink(handle, transaction=txn_handle)
        # when a resident pin covers this table, tee the written rows
        # through a DeltaTap so the pin can absorb the insert on its
        # delta side instead of being evicted
        from trino_tpu.resident import fastlane as _fastlane

        tap = _fastlane.delta_tap(
            cat, schema, table, [c.name for c in meta.columns]
        )
        if tap is not None:
            sink_impl = _fastlane.TeeSink(sink_impl, tap)
        writer = TableWriterOperator(sink_impl)
        chain.append(writer)
        for p in pipelines:
            Driver(p).run()
        Driver(Pipeline(chain)).run()
        _raise_deferred_checks(ctx)
        self._invalidate_plans(
            table=(cat, schema, table), appended=True, tap=tap
        )
        return MaterializedResult([[writer.rows_written]], ["rows"], [T.BIGINT])

    def _run_tracked(self, sql: str, stmt: ast.Query) -> MaterializedResult:
        """Query lifecycle: span tree + event listener dispatch around
        the actual execution (SqlQueryExecution's tracing shape)."""
        import time as _time

        from trino_tpu.runtime.events import QueryCreatedEvent
        from trino_tpu.runtime.metrics import METRICS
        from trino_tpu.runtime.tracing import KIND_QUERY, QueryTrace

        self._query_seq += 1
        query_id = f"local-{self._query_seq}"
        trace = QueryTrace(query_id)
        qspan = trace.span(f"query {query_id}", KIND_QUERY, sql=sql[:500])
        counters_before = METRICS.snapshot()
        self.event_listeners.query_created(
            QueryCreatedEvent(query_id, sql, _time.time())
        )
        status, failure, rows_n = "finished", None, 0
        try:
            result = self._execute_query(
                stmt, sql_key=sql, query_id=query_id,
                trace=trace, query_span=qspan,
            )
            rows_n = len(result.rows)
            return result
        except BaseException as e:
            status, failure = "failed", repr(e)
            if not qspan.ended:
                qspan.event("exception", error=repr(e)[:300])
                qspan.set(error=True)
            raise
        finally:
            self._finalize_query(
                query_id, sql, trace, qspan, status, failure, rows_n,
                counters_before,
            )

    def _finalize_query(self, query_id, sql, trace, qspan, status,
                        failure, rows_n, counters_before):
        """Close the span tree, retire per-query compile counters, and
        fire the enriched completion event. Observability finalization
        must never mask the query's own verdict, so it swallows."""
        try:
            from trino_tpu.exec.stats import engine_counters_delta
            from trino_tpu.runtime.events import QueryCompletedEvent
            from trino_tpu.runtime.metrics import (
                METRICS,
                retire_query_compiles,
            )

            qspan.set(state=status)
            qspan.end()
            trace.end_open_spans(qspan.end_s)
            wall = qspan.duration_s
            METRICS.observe("query_wall_s", wall)
            compile_count = retire_query_compiles(query_id)
            counters = engine_counters_delta(
                counters_before, METRICS.snapshot()
            )
            peak = 0
            if self._last_pool is not None:
                peaks = self._last_pool.query_peaks()
                peak = int(max(peaks.values(), default=0))
            self._last_trace = (query_id, trace)
            self.event_listeners.query_completed(
                QueryCompletedEvent(
                    query_id, sql, status, wall,
                    rows=rows_n, failure=failure,
                    peak_memory_bytes=peak,
                    rows_scanned=int(counters.get("rows_scanned", 0)),
                    bytes_scanned=int(counters.get("bytes_scanned", 0)),
                    rows_shuffled=int(counters.get("rows_shuffled", 0)),
                    compile_count=compile_count,
                )
            )
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "query finalization failed for %s", query_id, exc_info=True
            )

    def _plan(self, q: ast.Query, sql_key: Optional[str], query_span=None):
        import contextlib

        self._last_adaptive_report = None  # set again if adaptive runs

        def phase(name):
            if query_span is None:
                return contextlib.nullcontext()
            from trino_tpu.runtime.tracing import KIND_PHASE

            return query_span.child(name, KIND_PHASE)

        # the key canonicalizes the statement through the formatter
        # (fixpoint-checked in PR 5) and folds in the plan-shaping
        # session properties + bound-parameter dtypes, so SET SESSION
        # and EXECUTE bindings take effect however they were invoked
        cache_key = None
        if sql_key is not None:
            try:
                from trino_tpu.sql.formatter import format_statement

                canonical = format_statement(q)
            except Exception:
                canonical = sql_key
            cache_key = self._plan_cache.key(
                canonical, self.session,
                getattr(self._bound_dtypes_tls, "value", None) or (),
            )
        cached = self._plan_cache.lookup(cache_key) if cache_key else None
        if cached is not None:
            # access control re-checks on every execution, cached or not
            self._check_scans(cached[0])
            return cached
        from trino_tpu.sql.analyzer import (
            plan_is_volatile,
            reset_volatile_plan,
        )

        # snapshot the generation BEFORE planning: a DDL landing while
        # we plan must win over our store below
        cache_generation = self._plan_cache.generation
        reset_volatile_plan()
        with phase("analyze"):
            output = self._analyze(q)
        self._check_scans(output)
        # adaptive execution: observe materialization barriers and
        # re-plan the remainder BEFORE physical planning; transformed
        # plans embed data snapshots so they never enter the plan cache
        adaptive_report = None
        from trino_tpu.adaptive import AdaptiveController

        controller = AdaptiveController(
            self.catalogs, self.session, span=query_span,
            stabilizer=self._make_stabilizer(),
        )
        if controller.enabled():
            with phase("adaptive"):
                output = controller.prepare(output)
            adaptive_report = controller.report
        self._last_adaptive_report = adaptive_report
        with phase("optimize"):
            planner = LocalPlanner(
                self.catalogs,
                batch_rows=self.session.batch_rows,
                target_splits=self.session.target_splits,
                dynamic_filtering=self.session.enable_dynamic_filtering,
                stabilizer=self._make_stabilizer(),
                mxu_join=self.session.mxu_join_enabled,
                mxu_join_min_work=self.session.mxu_join_min_work,
            )
            physical = planner.plan(output)
        # plans with analysis-time-folded volatile values (now(),
        # current_date, uuid()) re-analyze every execution
        if (
            cache_key
            and not plan_is_volatile()
            and not (adaptive_report is not None and adaptive_report.transformed)
        ):
            from trino_tpu.serving.plan_cache import plan_tables

            self._plan_cache.store(
                cache_key, (output, physical), generation=cache_generation,
                tables=plan_tables(output),
            )
        return output, physical

    def _execution_ctx(self) -> dict:
        ctx: dict = {}
        if self.session.memory_pool_bytes is not None:
            from trino_tpu.runtime.memory import MemoryPool

            ctx["memory_pool"] = MemoryPool(self.session.memory_pool_bytes)
            # register resident pins revocable in this query's pool: a
            # reservation that cannot fit reclaims warm state BEFORE the
            # exhaustion handler considers killing a query
            from trino_tpu.resident import RESIDENT

            RESIDENT.attach_pool(ctx["memory_pool"])
        return ctx

    def _make_stabilizer(self):
        """Session's capacity policy (compile/shapes.py); None when
        shape stabilization is off."""
        if not getattr(self.session, "shape_stabilization", True):
            return None
        from trino_tpu.compile.shapes import CapacityLadder, ShapeStabilizer

        return ShapeStabilizer(
            CapacityLadder(
                base=getattr(self.session, "capacity_ladder_base", 2)
            ),
            batch_rows=self.session.batch_rows,
        )

    def _start_warmup(self, physical):
        """Kick off census-driven AOT warmup per warmup_mode; returns
        the (started) WarmupService or None. mode=block waits here, so
        execution starts with every predicted program compiled."""
        mode = getattr(self.session, "warmup_mode", "off")
        entries = getattr(physical, "warmup_entries", ())
        if mode == "off" or not entries:
            return None
        from trino_tpu.compile.warmup import WarmupService

        svc = WarmupService(entries, mode=mode).start()
        if mode == "block":
            svc.wait()
        return svc

    def _attribution_id(self) -> str:
        self._query_seq += 1
        return f"local-{self._query_seq}"

    # -- observability surface (runtime/tracing.py) --
    def query_trace_export(self, query_id: Optional[str] = None):
        """Span tree of the most recent query (the local runner keeps
        only the last trace); None when the id does not match."""
        if self._last_trace is None:
            return None
        qid, trace = self._last_trace
        if query_id is not None and query_id != qid:
            return None
        return trace.export()

    def query_chrome_trace(self, query_id: Optional[str] = None):
        from trino_tpu.runtime.tracing import chrome_trace

        export = self.query_trace_export(query_id)
        if export is None:
            return None
        return {"traceEvents": chrome_trace(export)}

    def _execute_query(
        self, q: ast.Query, sql_key: Optional[str] = None,
        query_id: Optional[str] = None, trace=None, query_span=None,
    ) -> MaterializedResult:
        import contextlib

        from trino_tpu.runtime.metrics import set_compile_attribution

        output, physical = self._plan(q, sql_key, query_span=query_span)
        self._start_warmup(physical)
        ctx = self._execution_ctx()
        self._last_pool = ctx.get("memory_pool")
        pipelines, chain = physical.instantiate(ctx)
        sink = CollectorSink()
        chain.append(sink)
        # compile attribution reuses the tracked query id, so the
        # per-query counter retired at finalization is the same one the
        # listener installed compiles under. Internal subqueries
        # (DELETE count rewrites, MERGE match checks) inherit the
        # enclosing statement's attribution so their compiles are
        # charged — and retired — with the user's query instead of
        # leaking one never-retired counter per helper
        from trino_tpu.runtime.metrics import compile_attribution

        prev_qid = set_compile_attribution(
            query_id or compile_attribution() or self._attribution_id()
        )
        exec_span = contextlib.nullcontext()
        if query_span is not None:
            from trino_tpu.runtime.tracing import KIND_PHASE

            exec_span = query_span.child("execute", KIND_PHASE)
        try:
            with exec_span:
                for p in pipelines:
                    Driver(p).run()
                Driver(Pipeline(chain)).run()
                checks = ctx.get("deferred_checks", ())
                rows, flags = sink.rows_with(tuple(f for f, _ in checks))
                for v, (_, msg) in zip(flags, checks):
                    if v:
                        raise RuntimeError(msg)
        finally:
            set_compile_attribution(prev_qid)
        return MaterializedResult(
            rows,
            list(output.names),
            [f.type for f in output.fields],
        )

    def _explain_analyze(self, q: ast.Query) -> MaterializedResult:
        """EXPLAIN ANALYZE: run with instrumented operators, render plan
        + per-operator stats (ExplainAnalyzeOperator analogue)."""
        from trino_tpu.exec.stats import (
            engine_counters_delta,
            instrument,
            render_stats,
        )
        from trino_tpu.runtime.metrics import (
            METRICS,
            install_xla_compile_listener,
            retire_query_compiles,
            set_compile_attribution,
        )
        from trino_tpu.sql.validate import census_text, shape_census

        install_xla_compile_listener()
        output, physical = self._plan(q, sql_key=None)
        stabilizer = self._make_stabilizer()
        classes = shape_census(
            output, self.catalogs,
            batch_rows=self.session.batch_rows,
            dynamic_filtering=self.session.enable_dynamic_filtering,
            ladder=stabilizer.ladder if stabilizer is not None else None,
        )
        warmup_svc = self._start_warmup(physical)
        qid = self._attribution_id()
        before = METRICS.snapshot()
        ctx = self._execution_ctx()
        pipelines, chain = physical.instantiate(ctx)
        sink = CollectorSink()
        chain.append(sink)
        groups = []
        wrapped_pipelines = []
        ledger = set()
        for p in pipelines:
            ops, stats = instrument(
                p.operators, device_sync=True, shape_ledger=ledger
            )
            groups.append(stats)
            wrapped_pipelines.append(Pipeline(ops))
        main_ops, main_stats = instrument(
            chain, device_sync=True, shape_ledger=ledger
        )
        groups.append(main_stats)
        prev_qid = set_compile_attribution(qid)
        try:
            for p in wrapped_pipelines:
                Driver(p).run()
            Driver(Pipeline(main_ops)).run()
        finally:
            set_compile_attribution(prev_qid)
        _raise_deferred_checks(ctx)
        for p in wrapped_pipelines:
            for op in p.operators:
                op.flush_counts()
        for op in main_ops:
            op.flush_counts()
        after = METRICS.snapshot()
        counters = engine_counters_delta(before, after)
        census = census_text(
            classes,
            warn_threshold=getattr(
                self.session, "compile_churn_warn_threshold", 0
            ),
            observed=len(ledger),
        )
        # compile-regime lines ride directly under the census: per-query
        # attributed compile count (satellite of the process-wide
        # xla_compiles engine counter), warmup hit/miss, cache stats
        qkey = f"xla_compiles_by_query.{qid}"
        compiled_here = int(after.get(qkey, 0.0) - before.get(qkey, 0.0))
        # EXPLAIN ANALYZE is this attribution id's terminal operation —
        # retire its counter so the registry stays bounded
        retire_query_compiles(qid)
        census += f"\nxla_compiles_this_query={compiled_here}"
        if warmup_svc is not None:
            if warmup_svc.mode == "background":
                # settle before reporting so entry statuses are final
                warmup_svc.wait(timeout=60.0)
            census += "\n" + warmup_svc.report_line(ledger)
        from trino_tpu.compile.cache import (
            ACTIVE_PERSISTENT_CACHE,
            PROGRAM_CACHE,
        )

        ps = PROGRAM_CACHE.stats()
        census += (
            f"\nprogram_cache: entries={ps['entries']} hits={ps['hits']} "
            f"misses={ps['misses']} evictions={ps['evictions']}"
        )
        if ACTIVE_PERSISTENT_CACHE is not None:
            cs = ACTIVE_PERSISTENT_CACHE.stats()
            census += (
                f"\npersistent_cache: entries={cs['entries']} "
                f"bytes={cs['bytes']} scrubbed={cs['scrubbed']} "
                f"evicted={cs['evicted']}"
            )
        # adaptive section: what the controller observed and did
        # (estimated_vs_observed per barrier, replan/spool counts)
        report = getattr(self, "_last_adaptive_report", None)
        if report is not None:
            census += "\n" + "\n".join(report.lines())
        # census goes AFTER the runtime stats: per-class lines name
        # operators too, and stats consumers grep for the first line
        # mentioning an operator
        text = (
            explain_text(output) + "\n\n"
            + render_stats(groups, counters) + "\n\n" + census
        )
        return MaterializedResult([[text]], ["Query Plan"], [T.VARCHAR])
