"""In-process query engine: SQL text in, rows out.

Analogue of Trino's LocalQueryRunner (main/testing/LocalQueryRunner.java:264
— plan and execute SQL fully in-process with real operators, SURVEY.md
§4.2) plus the session/catalog surface of Session + MetadataManager.
The distributed runner (coordinator/worker split over fragments) layers
on top of the same plans.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

from trino_tpu import types as T
from trino_tpu.connectors.spi import CatalogManager, Connector
from trino_tpu.exec import CollectorSink, Driver, Pipeline
from trino_tpu.sql import ast
from trino_tpu.sql.analyzer import AnalysisError, Analyzer
from trino_tpu.sql.local_planner import LocalPlanner
from trino_tpu.sql.parser import parse
from trino_tpu.sql.plan import OutputNode, explain_text


@dataclasses.dataclass
class Session:
    """Per-query context (main/Session.java analogue; properties grow
    with the session-property system). retry_policy mirrors Trino's
    `retry_policy` session property: "none" (pipelined), "query"
    (whole-query retry inside the pipelined scheduler,
    PipelinedQueryScheduler.scheduleRetryWithDelay:394) or "task"
    (FTE over spooled exchange, SURVEY.md §3.5)."""

    catalog: str = "tpch"
    schema: str = "tiny"
    batch_rows: int = 1 << 20
    target_splits: int = 1
    retry_policy: str = "none"
    query_retries: int = 2
    task_retries: int = 3
    # per-query memory budget (None = unlimited); exceeding it triggers
    # revocation/spill, then ExceededMemoryLimitError
    memory_pool_bytes: Optional[int] = None


@dataclasses.dataclass
class MaterializedResult:
    """QueryAssertions' MaterializedResult analogue."""

    rows: List[list]
    column_names: List[str]
    column_types: List[T.DataType]

    def only_value(self):
        assert len(self.rows) == 1 and len(self.rows[0]) == 1, self.rows
        return self.rows[0][0]


class LocalQueryRunner:
    def __init__(self, session: Optional[Session] = None):
        self.session = session or Session()
        self.catalogs = CatalogManager()
        # SQL text -> (OutputNode, PhysicalPlan): re-executing a cached
        # query reuses every jitted device program (the reference's
        # expression/operator caches keyed on expression, §2.9)
        self._plan_cache: dict = {}

    def register_catalog(self, name: str, connector: Connector) -> None:
        self.catalogs.register(name, connector)

    # -- entry point --
    def execute(self, sql: str) -> MaterializedResult:
        stmt = parse(sql)
        if isinstance(stmt, ast.Query):
            return self._execute_query(stmt, sql_key=sql)
        if isinstance(stmt, ast.ExplainStatement):
            plan = self._analyze(stmt.query)
            return MaterializedResult(
                [[explain_text(plan)]], ["Query Plan"], [T.VARCHAR]
            )
        if isinstance(stmt, ast.ShowSchemas):
            cat = stmt.catalog or self.session.catalog
            conn = self.catalogs.get(cat)
            rows = [[s] for s in conn.metadata.list_schemas()]
            return MaterializedResult(rows, ["Schema"], [T.VARCHAR])
        if isinstance(stmt, ast.ShowTables):
            cat, schema = self.session.catalog, self.session.schema
            if stmt.schema:
                if len(stmt.schema) == 2:
                    cat, schema = stmt.schema
                else:
                    schema = stmt.schema[0]
            conn = self.catalogs.get(cat)
            rows = [[t] for t in conn.metadata.list_tables(schema)]
            return MaterializedResult(rows, ["Table"], [T.VARCHAR])
        if isinstance(stmt, ast.ShowColumns):
            parts = stmt.table
            cat, schema = self.session.catalog, self.session.schema
            table = parts[-1]
            if len(parts) == 2:
                schema = parts[0]
            elif len(parts) == 3:
                cat, schema = parts[0], parts[1]
            conn, handle = self.catalogs.resolve_table(cat, schema, table)
            meta = conn.metadata.get_table_metadata(handle)
            rows = [[c.name, str(c.type)] for c in meta.columns]
            return MaterializedResult(rows, ["Column", "Type"], [T.VARCHAR, T.VARCHAR])
        raise AnalysisError(f"cannot execute {type(stmt).__name__}")

    def _analyze(self, q: ast.Query) -> OutputNode:
        analyzer = Analyzer(self.catalogs, self.session.catalog, self.session.schema)
        return analyzer.plan(q)

    def _execute_query(self, q: ast.Query, sql_key: Optional[str] = None) -> MaterializedResult:
        cached = self._plan_cache.get(sql_key) if sql_key else None
        if cached is None:
            output = self._analyze(q)
            planner = LocalPlanner(
                self.catalogs,
                batch_rows=self.session.batch_rows,
                target_splits=self.session.target_splits,
            )
            physical = planner.plan(output)
            if sql_key:
                self._plan_cache[sql_key] = (output, physical)
        else:
            output, physical = cached
        ctx: dict = {}
        if self.session.memory_pool_bytes is not None:
            from trino_tpu.runtime.memory import MemoryPool

            ctx["memory_pool"] = MemoryPool(self.session.memory_pool_bytes)
        pipelines, chain = physical.instantiate(ctx)
        sink = CollectorSink()
        chain.append(sink)
        for p in pipelines:
            Driver(p).run()
        Driver(Pipeline(chain)).run()
        return MaterializedResult(
            sink.rows(),
            list(output.names),
            [f.type for f in output.fields],
        )
