"""Managed compile caches: in-process program cache + persistent XLA
compilation-cache directory.

Two tiers, different lifetimes:

**ProgramCache** (in-process, cross-query). The planner builds one
`jax.jit` wrapper per fused filter/project stage; identical SQL
replanned later — a dynamic-filter retry, an FTE re-attempt, a
restarted LocalQueryRunner in the same process — rebuilds a
semantically identical wrapper, and jax treats distinct Python
callables as distinct jit caches. The ProgramCache closes that hole:
stages are keyed on their *structural* identity (frozen-dataclass expr
reprs + the input schema signature including dictionary values) and
the planner reuses the exact same jitted callable, so the re-plan
dispatches straight into jax's already-populated C++ fast path with
zero new lowerings.

**PersistentCompileCache** (on-disk, cross-process). Promotes the bare
`jax_compilation_cache_dir` wiring that used to live in jaxcfg.py into
a managed directory: entries live under a versioned salt directory
(`<root>/jax<version>-schema<rev>/`) so a jax upgrade or an engine
schema-rev bump starts a fresh namespace instead of deserializing
stale executables; startup scrubs zero-byte / orphaned-tmp entries
(a process killed mid-write must not poison successors); total size is
LRU-bounded by file mtime; hit/evict/scrub counts feed METRICS. The
CPU-platform opt-out and the 5 s min-compile-time floor are preserved
from jaxcfg (XLA:CPU AOT entries can SIGILL on reload).
"""

from __future__ import annotations

import os
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

# Bump when the engine's batch layout / kernel calling conventions
# change in a way that invalidates cached executables' applicability
# (the salt below keys the persistent cache namespace on it).
ENGINE_SCHEMA_REV = 1

_MB = 1 << 20


class ProgramCache:
    """Thread-safe LRU of structurally-keyed jitted callables.

    jax.jit returns a C++ PjitFunction that rejects attribute
    assignment, so the reverse mapping (callable -> key, used by the
    planner to key *compositions* of cached stages) is an id() side
    table rather than an attribute."""

    def __init__(self, max_entries: int = 1024):
        self._max_entries = max_entries
        self._lock = named_lock("ProgramCache._lock")
        self._entries: "OrderedDict[Any, Any]" = OrderedDict()  # guarded_by: _lock
        self._keys_by_id: Dict[int, Any] = {}  # guarded_by: _lock
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get_or_create(self, key: Any, builder: Callable[[], Any]) -> Any:
        with self._lock:
            fn = self._entries.get(key)
            if fn is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return fn
        # build outside the lock (jit wrapper construction is cheap but
        # may import); racing builders are benign — first insert wins
        fn = builder()
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                self.hits += 1
                return existing
            self.misses += 1
            self._entries[key] = fn
            self._keys_by_id[id(fn)] = key
            while len(self._entries) > self._max_entries:
                _, old = self._entries.popitem(last=False)
                self._keys_by_id.pop(id(old), None)
                self.evictions += 1
        return fn

    def key_of(self, fn: Any) -> Optional[Any]:
        with self._lock:
            return self._keys_by_id.get(id(fn))

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._keys_by_id.clear()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }

    def fingerprints(self) -> list:
        """Stable string forms of every cached program key, in LRU
        order — the joining-host warm manifest (runtime/fabric.py)
        ships these so a new host can see which program identities the
        pod has compiled (observability: keys are structural tuples,
        repr is their canonical printable form)."""
        with self._lock:
            return [repr(k) for k in self._entries]


# the process singleton the planner uses
PROGRAM_CACHE = ProgramCache()


def schema_cache_key(schema) -> Optional[tuple]:
    """Structural signature of a [(DataType, Dictionary|None)] schema,
    dictionary *values* included — two plans over equal-typed columns
    with different string dictionaries bind different device constants
    and must not share a program. Returns None (uncacheable) for
    RuntimeDictionary columns, whose values only exist at execution
    time."""
    from trino_tpu.block import Dictionary

    parts = []
    for typ, d in schema:
        if d is None:
            dk = None
        elif type(d) is Dictionary:
            dk = d.values
        else:  # RuntimeDictionary (or future subclasses): bail out
            return None
        parts.append((str(typ), dk))
    return tuple(parts)


def expr_fingerprint(*parts) -> Optional[str]:
    """Deterministic fingerprint from expr-IR reprs. The IR nodes are
    frozen dataclasses whose repr is purely structural; a defensive
    check rejects anything that leaked an object address (default
    object repr) into the string."""
    fp = repr(parts)
    if " object at 0x" in fp:
        return None
    return fp


class PersistentCompileCache:
    """Managed on-disk XLA compilation cache (see module docstring)."""

    def __init__(self, root: Optional[str] = None,
                 max_bytes: Optional[int] = None):
        import jax

        self.root = root or os.environ.get(
            "TRINO_TPU_COMPILE_CACHE",
            os.path.expanduser("~/.trino_tpu_xla_cache"),
        )
        self.salt = f"jax{jax.__version__}-schema{ENGINE_SCHEMA_REV}"
        self.dir = os.path.join(self.root, self.salt)
        if max_bytes is None:
            max_bytes = int(
                os.environ.get("TRINO_TPU_COMPILE_CACHE_MAX_MB", "1024")
            ) * _MB
        self.max_bytes = max_bytes
        self.scrubbed = 0
        self.evicted = 0

    # -- directory maintenance ------------------------------------------

    def _entries(self):
        """[(path, size, mtime)] for regular files under the salt dir."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            p = os.path.join(self.dir, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            if os.path.isfile(p):
                out.append((p, st.st_size, st.st_mtime))
        return out

    def scrub(self) -> int:
        """Corruption-tolerant startup scrub: drop zero-byte entries and
        orphaned temp files (a writer killed mid-rename leaves both).
        jax verifies entry checksums on read, so deeper corruption
        degrades to a cache miss — the scrub just keeps the directory
        from accumulating dead weight."""
        removed = 0
        for p, size, _ in self._entries():
            base = os.path.basename(p)
            if size == 0 or base.endswith(".tmp") or base.startswith("tmp"):
                try:
                    os.remove(p)
                    removed += 1
                except OSError:
                    pass
        self.scrubbed += removed
        if removed:
            _metrics_increment("compile_cache_scrubbed", removed)
        return removed

    def evict(self) -> int:
        """Size-bounded LRU: oldest-mtime entries go first until the
        salt dir fits max_bytes."""
        entries = sorted(self._entries(), key=lambda e: e[2])
        total = sum(size for _, size, _ in entries)
        removed = 0
        for p, size, _ in entries:
            if total <= self.max_bytes:
                break
            try:
                os.remove(p)
            except OSError:
                continue
            total -= size
            removed += 1
        self.evicted += removed
        if removed:
            _metrics_increment("compile_cache_evictions", removed)
        return removed

    def prepare(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        self.scrub()
        self.evict()

    # -- activation ------------------------------------------------------

    def activate(self) -> bool:
        """Point jax's persistent compilation cache at the managed salt
        directory. Returns False (cache disabled, engine fully
        functional) on any failure — the cache is an optimization."""
        import jax

        try:
            self.prepare()
            jax.config.update("jax_compilation_cache_dir", self.dir)
            # 5s floor keeps XLA:CPU programs (sub-second compiles) out
            # of the cache even when JAX silently falls back to CPU —
            # CPU AOT entries record compile-option pseudo-features the
            # loader rejects on reload (can SIGILL)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 5.0
            )
        except Exception:
            return False
        install_cache_event_listener()
        return True

    # -- observability ---------------------------------------------------

    def entry_count(self) -> int:
        return len(self._entries())

    def total_bytes(self) -> int:
        return sum(size for _, size, _ in self._entries())

    def stats(self) -> Dict[str, Any]:
        return {
            "dir": self.dir,
            "entries": self.entry_count(),
            "bytes": self.total_bytes(),
            "max_bytes": self.max_bytes,
            "scrubbed": self.scrubbed,
            "evicted": self.evicted,
        }


# the active persistent cache, if configure_persistent_cache enabled one
ACTIVE_PERSISTENT_CACHE: Optional[PersistentCompileCache] = None

_cache_listener_installed = False


def _metrics_increment(name: str, delta: float = 1.0) -> None:
    try:
        from trino_tpu.runtime.metrics import METRICS

        METRICS.increment(name, delta)
    except Exception:
        pass


def install_cache_event_listener() -> bool:
    """Count persistent-cache hits/misses via jax.monitoring (jax
    records `/jax/compilation_cache/cache_hits` style events around
    disk-cache lookups). Idempotent; tolerant of jax builds that emit
    neither event."""
    global _cache_listener_installed
    if _cache_listener_installed:
        return True
    try:
        from jax import monitoring

        def _on_event(event: str, **kw) -> None:
            if "compilation_cache" not in event:
                return
            if "hit" in event:
                _metrics_increment("compile_cache_hits")
            elif "miss" in event:
                _metrics_increment("compile_cache_misses")

        monitoring.register_event_listener(_on_event)
    except Exception:
        return False
    _cache_listener_installed = True
    return True


def configure_persistent_cache() -> Optional[PersistentCompileCache]:
    """jaxcfg entry point, run once at import. TPU-targeted processes
    only (see PersistentCompileCache.activate for the CPU rationale);
    opt out entirely with TRINO_TPU_NO_COMPILE_CACHE=1."""
    global ACTIVE_PERSISTENT_CACHE
    if ACTIVE_PERSISTENT_CACHE is not None:
        return ACTIVE_PERSISTENT_CACHE
    if (
        os.environ.get("TRINO_TPU_NO_COMPILE_CACHE") == "1"
        or "cpu" in os.environ.get("JAX_PLATFORMS", "")
    ):
        return None
    cache = PersistentCompileCache()
    if cache.activate():
        ACTIVE_PERSISTENT_CACHE = cache
        return cache
    return None
