"""Census-driven AOT warmup.

At plan time the LocalPlanner records, for every fused filter/project
stage it builds, a WarmupEntry: the jitted callable, its input schema,
and the capacity classes the shape census predicts the stage will see
(the stabilized scan classes of the chain feeding it — main class plus
the tail class for tables larger than batch_rows). The WarmupService
then drives each callable once per predicted capacity on an all-dead
zero batch, populating jax's jit dispatch cache ahead of first touch.

Why execute a zero batch instead of `.lower().compile()`: the AOT path
produces a separate Compiled object whose executable is not guaranteed
to seed the jit wrapper's own dispatch cache on this jax version, so a
"warmed" program could still compile again on first real call. Calling
the wrapper itself with a dead batch (live mask all False — operators
never read dead lanes, so the execution cost is one masked pass over
zeros) is the warm path the query will actually take. jax's internal
locking gives first-touch pipelining for free: the background thread
compiles entries in order while the query runs, and execution blocks
only if it reaches a program mid-compile — never on programs it does
not need.

Failure policy: a warmup failure marks the entry "failed" and moves
on; the query compiles that program on demand exactly as without
warmup. Warmup can slow a query down at worst — never fail it.

The module also owns WARM_CLASSES, the process-global registry of
(operator, capacity, dtype-sig) classes known compiled — fed by warmup
compiles and by successfully completed tasks — which the stuck-task
watchdog consults to apply the aggressive `stuck_task_interrupt_warm_s`
threshold only to tasks whose predicted classes are all warm (a cold
compile burst can no longer be mistaken for a hang).
"""

from __future__ import annotations

import dataclasses
import threading
from trino_tpu.analysis import threadreg
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import Iterable, Optional, Sequence, Set, Tuple

# (operator, capacity, dtype-sig) classes proven compiled in this
# process — the same vocabulary as the shape ledger (exec/stats.py)
# and the census (sql/validate.py Lowering).
WARM_CLASSES: Set[Tuple] = set()
_warm_lock = named_lock("warmup._warm_lock")


def note_classes_warm(keys: Iterable[Tuple]) -> None:
    """Record classes as compiled (warmup success or task completion)."""
    with _warm_lock:
        WARM_CLASSES.update(keys)


def classes_warm(keys: Iterable[Tuple]) -> bool:
    """True when every key is already registered warm (and there is at
    least one key — an empty prediction proves nothing)."""
    ks = set(keys)
    if not ks:
        return False
    with _warm_lock:
        return ks <= WARM_CLASSES


def reset_warm_classes() -> None:
    """Test hook: forget everything (a fresh 'process')."""
    with _warm_lock:
        WARM_CLASSES.clear()


def warm_manifest() -> list:
    """JSON-serializable snapshot of the warm-class registry, sorted
    for determinism — the payload a joining host replays (multi-host
    fabric warm join) so its first placed query mints zero new
    lowerings for classes the pod has already proven."""
    with _warm_lock:
        keys = sorted(WARM_CLASSES)
    return [[op, int(cap), list(dts)] for op, cap, dts in keys]


def apply_manifest(manifest) -> int:
    """Install a warm-class manifest produced by `warm_manifest` on
    another host. Malformed items are skipped, never raised — a bad
    manifest degrades to on-demand compilation, not to failure.
    Returns the number of classes applied."""
    keys = []
    for item in manifest or []:
        try:
            op, cap, dts = item
            keys.append(
                (str(op), int(cap), tuple(str(d) for d in dts))
            )
        except Exception:
            continue
    note_classes_warm(keys)
    return len(keys)


@dataclasses.dataclass
class WarmupEntry:
    """One fused stage to precompile across its predicted capacities."""

    operator: str  # ledger/census operator name ("FilterProjectOperator")
    fn: object  # the jitted batch->batch callable
    in_schema: Sequence  # [(DataType, Dictionary|None)] feeding fn
    out_dtypes: Tuple[str, ...]  # output column type strs (ledger sig)
    capacities: Tuple[int, ...]
    status: str = "pending"  # pending | compiled | failed | skipped
    detail: str = ""

    def keys(self) -> Set[Tuple]:
        return {(self.operator, c, self.out_dtypes) for c in self.capacities}


def zeros_batch(schema, capacity: int):
    """All-dead batch of the given schema at the given capacity: zero
    data, live mask all False. Raises for nested types (array/row zero
    layouts are operator-specific; those entries degrade to
    on-demand)."""
    import jax.numpy as jnp

    from trino_tpu.block import Column, RelBatch

    cols = []
    for typ, d in schema:
        if getattr(typ, "is_nested", False):
            raise NotImplementedError(f"nested warmup unsupported: {typ}")
        cols.append(Column(typ, jnp.zeros((capacity,), dtype=typ.dtype), None, d))
    return RelBatch(cols, jnp.zeros((capacity,), dtype=bool))


class WarmupService:
    """Drives a list of WarmupEntry to compiled status.

    mode="background": compile on a daemon thread while the query runs.
    mode="block": same thread work, but the caller wait()s before
    execution starts (deterministic cold-start measurement, tests).
    """

    def __init__(self, entries: Sequence[WarmupEntry], mode: str = "background"):
        self.entries = list(entries)
        self.mode = mode
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- driving ---------------------------------------------------------

    def start(self) -> "WarmupService":
        if self.mode == "off" or not self.entries:
            self._done.set()
            return self
        self._thread = threadreg.spawn(
            "trino-tpu-warmup", self._run, owner="WarmupService"
        )
        return self

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def _run(self) -> None:
        try:
            for entry in self.entries:
                self._warm_entry(entry)
        finally:
            self._done.set()

    def _warm_entry(self, entry: WarmupEntry) -> None:
        from trino_tpu.runtime.metrics import METRICS

        compiled = 0
        for cap in entry.capacities:
            key = (entry.operator, cap, entry.out_dtypes)
            try:
                batch = zeros_batch(entry.in_schema, cap)
            except Exception as ex:
                entry.status = "skipped"
                entry.detail = str(ex)
                METRICS.increment("warmup_skipped")
                return
            try:
                entry.fn(batch)
            except Exception as ex:
                entry.status = "failed"
                entry.detail = str(ex)
                METRICS.increment("warmup_failures")
                return  # degrade to on-demand compile; never fail the query
            note_classes_warm([key])
            compiled += 1
            METRICS.increment("warmup_compiles")
        entry.status = "compiled"
        entry.detail = f"{compiled} capacities"

    # -- reporting -------------------------------------------------------

    def warmed_keys(self) -> Set[Tuple]:
        out: Set[Tuple] = set()
        for e in self.entries:
            if e.status == "compiled":
                out |= e.keys()
        return out

    def status_counts(self):
        counts = {"compiled": 0, "failed": 0, "skipped": 0, "pending": 0}
        for e in self.entries:
            counts[e.status] = counts.get(e.status, 0) + 1
        return counts

    def report_line(self, ledger: Optional[Set[Tuple]] = None) -> str:
        """EXPLAIN ANALYZE line, printed next to the census. Hits are
        warmed classes the query actually executed; misses are observed
        classes warmup did not cover (compiled on demand — scans,
        aggregates, and any failed/skipped entries)."""
        c = self.status_counts()
        line = (
            f"warmup: mode={self.mode} entries={len(self.entries)} "
            f"compiled={c['compiled']} failed={c['failed']} "
            f"skipped={c['skipped']}"
        )
        if ledger is not None:
            warmed = self.warmed_keys()
            hits = len(warmed & ledger)
            misses = len(ledger - warmed)
            line += f" hits={hits} misses={misses}"
        return line

    def plan_text(self) -> str:
        """Deterministic pre-execution listing (explain_corpus)."""
        lines = [f"Warmup plan: mode={self.mode} entries={len(self.entries)}"]
        for e in self.entries:
            caps = ",".join(str(c) for c in e.capacities)
            lines.append(f"  {e.operator} caps=[{caps}] [{', '.join(e.out_dtypes)}]")
        return "\n".join(lines)
