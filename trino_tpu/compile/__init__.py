"""Compile regime: shape stabilization, census-driven warmup, and
managed XLA compile caches.

Three cooperating parts (see README "Compile regime & warmup"):

- `shapes`  — the capacity-class ladder and the per-plan
  ShapeStabilizer policy that pads operator-facing batches (pruned
  scans, tail chunks, spill re-reads) onto a small closed set of
  capacity classes so retries re-land on already-compiled lowerings.
- `warmup`  — a warmup service fed by the static shape census
  (sql/validate.py) that precompiles predicted lowerings ahead of
  first touch, plus the process-wide WARM_CLASSES registry consulted
  by the stuck-task watchdog.
- `cache`   — the in-process keyed program cache (cross-query jit
  reuse) and the managed persistent XLA compilation-cache directory
  (salted layout, startup scrub, size-bounded LRU eviction).

Submodules are imported lazily by callers, not here: `cache` is pulled
in by jaxcfg during early interpreter startup and must not drag the
whole package (and its block.py dependency) with it.
"""

__all__ = ["shapes", "warmup", "cache"]
