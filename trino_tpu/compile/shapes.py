"""Shape stabilization: the capacity-class ladder.

The engine's static-shape discipline compiles one XLA program per
(operator, capacity, dtype-sig). Anything that makes batch capacities
data-dependent — connector pushdown pruning, dynamic-filter pruning,
tail chunks of large tables, spill re-reads — mints fresh capacities
and therefore fresh lowerings, which is exactly the compile churn the
shape census (sql/validate.py) was built to count.

The fix is a *policy*, not a mechanism: batches already carry a `live`
mask, so any batch can be padded to a larger capacity for free. The
CapacityLadder defines the closed set of admissible capacities and the
ShapeStabilizer decides which rung each batch lands on:

- **Scan chunks pad to the rung of their pre-pruning span.** A chunk
  covering source rows [a, b) pads to rung(b - a) no matter how many
  rows survive pushdown or dynamic-filter pruning. That makes the
  runtime capacity a function of table size and batch_rows alone —
  statically predictable by the census, identical across retries, and
  independent of selectivity estimates. The tail chunk of a table
  larger than batch_rows lands on its own (smaller, equally
  predictable) rung.
- **Spill re-reads restore their original capacity** (exec/spill.py
  records it per entry), so an unspilled batch re-enters the operator
  on the class it was first compiled for.

The default ladder (base=2) is exactly the `bucket_capacity` power-of-
two grid, so stabilization changes *which* rung a pruned batch lands on
(its span's, not its survivor-count's) without introducing any new
capacities. A coarser base (capacity_ladder_base session property)
trades padding waste for fewer classes.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from trino_tpu.block import MIN_CAPACITY, bucket_capacity


@dataclasses.dataclass(frozen=True)
class CapacityLadder:
    """The closed set of admissible batch capacities: min_capacity,
    min_capacity*base, min_capacity*base^2, ... Base must be a power of
    two so every rung stays on the bucket_capacity grid (device
    kernels assume power-of-two capacities)."""

    base: int = 2
    min_capacity: int = MIN_CAPACITY

    def __post_init__(self):
        if self.base < 2 or (self.base & (self.base - 1)) != 0:
            raise ValueError(f"ladder base must be a power of two >= 2, got {self.base}")
        if self.min_capacity < MIN_CAPACITY or (
            self.min_capacity & (self.min_capacity - 1)
        ) != 0:
            raise ValueError(
                f"ladder min_capacity must be a power of two >= {MIN_CAPACITY}"
            )

    def rung(self, n: int) -> int:
        """Smallest rung >= n (>= min_capacity for n <= min_capacity)."""
        c = bucket_capacity(max(int(n), 1))
        r = self.min_capacity
        while r < c:
            r *= self.base
        return r

    def rungs(self, up_to: int) -> List[int]:
        """All rungs <= rung(up_to), ascending."""
        out = [self.min_capacity]
        top = self.rung(up_to)
        while out[-1] < top:
            out.append(out[-1] * self.base)
        return out


class ShapeStabilizer:
    """Per-plan capacity policy: maps row spans/counts onto ladder
    rungs. Created by the engine per (session, plan) from the
    shape_stabilization / capacity_ladder_base session properties and
    threaded through LocalPlanner into connector page sources."""

    def __init__(self, ladder: Optional[CapacityLadder] = None,
                 batch_rows: int = 1 << 20):
        self.ladder = ladder or CapacityLadder()
        self.batch_rows = int(batch_rows)

    def chunk_capacity(self, span_rows: int) -> int:
        """Capacity for a scan chunk spanning `span_rows` source rows
        BEFORE pruning. Pruned chunks re-land on the unpruned class.
        No batch_rows clamp: generator-backed sources (tpch lineitem)
        can emit more rows per chunk than the nominal batch_rows and
        the capacity must cover every generated row."""
        return self.ladder.rung(span_rows)

    def page_capacity(self, row_count: int, floor: Optional[int] = None) -> int:
        """Capacity for a materialized page (exchange / spill re-read):
        the rung of its live row count, optionally floored to a known
        class so small pages join a larger closed set."""
        cap = self.ladder.rung(max(int(row_count), 1))
        if floor:
            cap = max(cap, int(floor))
        return cap

    def scan_classes(self, table_rows: float,
                     batch_rows: Optional[int] = None) -> Tuple[int, ...]:
        """Predicted chunk capacity classes for scanning a table of
        `table_rows` rows: the main class plus (for tables larger than
        batch_rows with a remainder) the tail class. This is the same
        arithmetic the shape census uses, so warmup precompiles exactly
        the classes the ledger will observe."""
        br = int(batch_rows or self.batch_rows)
        rows = int(max(table_rows, 1))
        caps = [self.ladder.rung(min(rows, br))]
        tail = rows % br if rows > br else 0
        if tail:
            t = self.ladder.rung(tail)
            if t not in caps:
                caps.append(t)
        return tuple(caps)
