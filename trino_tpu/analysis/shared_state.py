"""Shared-state lint: unlocked global writes, guarded fields, raw threads.

Three checks over the same parsed modules as the lock-order pass:

1. **unlocked-global-write** — a module-level mutable container
   (``LAST_RUN_INFO``-style dict, ``MESH_WARMUP_ENTRIES``-style list,
   ``WARM_CLASSES``-style set) mutated inside a function without a lock
   lexically held.  Exempt: module import time, functions named
   ``reset_*`` / ``_reset*`` (the single-threaded test-reset init path),
   and sites carrying a trailing ``# unlocked-ok: <reason>`` comment.

2. **guarded-field** — the ``# guarded_by: <lock>`` convention.  A
   trailing comment on a field initialisation
   (``self._entries = ...  # guarded_by: _lock`` in ``__init__``, or a
   module global) declares its guard; every later read or write of that
   field must happen with the guard lexically held, in a method whose
   name ends in ``_locked`` (the held-by-caller convention this codebase
   already uses), in ``__init__``/``__new__``, or under a trailing
   ``# unguarded-ok: <reason>``.

3. **unregistered-thread** — a direct ``threading.Thread(...)`` call
   anywhere in the package.  Background threads must go through
   ``analysis.threadreg.spawn`` so they carry a name and an owner; the
   registry's own spawn site is marked ``# thread-ok``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from trino_tpu.analysis.lockgraph import (
    Finding, LockGraphResult, _ClassInfo, _FuncInfo, _ModuleInfo, _Resolver,
    _line_has,
)

__all__ = ["scan_shared_state"]

_MUTABLE_CTORS = {"dict", "list", "set", "defaultdict", "OrderedDict", "deque", "Counter"}
_MUTATORS = {
    "update", "clear", "append", "extend", "add", "remove", "discard",
    "pop", "popitem", "setdefault", "insert", "appendleft", "popleft",
}
_GUARD_RE = re.compile(r"#\s*guarded_by:\s*([A-Za-z_][\w.]*)")


def _mutable_globals(mod: _ModuleInfo) -> Dict[str, int]:
    """NAME -> def line for module-level mutable container globals."""
    out: Dict[str, int] = {}
    for node in mod.tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            if len(targets) != 1 or not isinstance(targets[0], ast.Name):
                continue
            value = node.value
            if value is None:
                continue
            name = targets[0].id
            if isinstance(value, (ast.Dict, ast.List, ast.Set)):
                out[name] = node.lineno
            elif isinstance(value, ast.Call):
                f = value.func
                ctor = f.id if isinstance(f, ast.Name) else (
                    f.attr if isinstance(f, ast.Attribute) else None)
                if ctor in _MUTABLE_CTORS:
                    out[name] = node.lineno
    return out


def _module_guards(mod: _ModuleInfo) -> Dict[str, str]:
    """NAME -> lock_id for `# guarded_by:` annotated module globals."""
    guards: Dict[str, str] = {}
    for node in mod.tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            if len(targets) != 1 or not isinstance(targets[0], ast.Name):
                continue
            m = _guard_on_line(mod, node.lineno)
            if m is None:
                continue
            lock_id = _resolve_guard_name(mod, None, m)
            if lock_id is not None:
                guards[targets[0].id] = lock_id
    return guards


def _class_guards(mod: _ModuleInfo, ci: _ClassInfo) -> Dict[str, str]:
    """attr -> lock_id for `# guarded_by:` annotated self.X inits."""
    guards: Dict[str, str] = {}
    for fi in ci.methods.values():
        for node in ast.walk(fi.node):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            if len(targets) != 1:
                continue
            t = targets[0]
            if not (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            m = _guard_on_line(mod, node.lineno)
            if m is None:
                continue
            lock_id = _resolve_guard_name(mod, ci, m)
            if lock_id is not None:
                guards[t.attr] = lock_id
    # class-body declarations: `x: int = 0  # guarded_by: _lock`
    for mnode in mod.tree.body:
        if isinstance(mnode, ast.ClassDef) and mnode.name == ci.name:
            for node in mnode.body:
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    if len(targets) == 1 and isinstance(targets[0], ast.Name):
                        m = _guard_on_line(mod, node.lineno)
                        if m is not None:
                            lock_id = _resolve_guard_name(mod, ci, m)
                            if lock_id is not None:
                                guards[targets[0].id] = lock_id
    return guards


def _guard_on_line(mod: _ModuleInfo, line: int) -> Optional[str]:
    if 1 <= line <= len(mod.lines):
        m = _GUARD_RE.search(mod.lines[line - 1])
        if m:
            return m.group(1)
    return None


def _resolve_guard_name(mod: _ModuleInfo, ci: Optional[_ClassInfo],
                        name: str) -> Optional[str]:
    """`_lock` -> the lock id of the class attr / module global."""
    if ci is not None and name in ci.lock_attrs:
        return ci.lock_attrs[name].lock_id
    if name in mod.locks:
        return mod.locks[name].lock_id
    if "." in name:
        return name  # already a fully-qualified lock id
    return None


def _assigned_locals(fn: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(names assigned in fn, names declared global/nonlocal)."""
    assigned: Set[str] = set()
    globals_: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            assigned.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            globals_.update(node.names)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store, ast.Del)):
            assigned.add(node.id)
        elif isinstance(node, (ast.For, ast.comprehension)):
            tgt = node.target
            for n in ast.walk(tgt):
                if isinstance(n, ast.Name):
                    assigned.add(n.id)
    return assigned, globals_


class _StateWalker:
    """Held-lock-aware walk of one function for state checks."""

    def __init__(self, res: _Resolver, mod: _ModuleInfo, ci: Optional[_ClassInfo],
                 fi: _FuncInfo, ctx: "_StateContext", findings: List[Finding]):
        self.res = res
        self.mod = mod
        self.ci = ci
        self.fi = fi
        self.ctx = ctx
        self.findings = findings
        self.fname = fi.node.name if hasattr(fi.node, "name") else "<lambda>"
        self.assigned, self.globals_ = _assigned_locals(fi.node)
        self.is_init = self.fname in ("__init__", "__new__", "__post_init__")
        self.is_locked_conv = self.fname.endswith("_locked")
        self.is_reset = self.fname.startswith("reset_") or self.fname.startswith("_reset")

    # -- helpers --
    def _global_ref(self, expr: ast.AST) -> Optional[Tuple[_ModuleInfo, str]]:
        """Resolve expr to (module, NAME) for a module-level global."""
        if isinstance(expr, ast.Name):
            name = expr.id
            if name in self.assigned and name not in self.globals_:
                return None  # shadowed by a local
            return (self.mod, name)
        if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)):
            alias = self.mod.import_mods.get(expr.value.id)
            if alias is not None:
                src = self.res.modules.get(alias)
                if src is not None:
                    return (src, expr.attr)
        return None

    def _suppressed(self, line: int, marker: str) -> bool:
        return _line_has(self.mod, line, marker)

    def _check_mutation(self, expr: ast.AST, held: Tuple[str, ...], line: int) -> None:
        ref = self._global_ref(expr)
        if ref is None:
            return
        src, name = ref
        if name not in self.ctx.mutable_globals.get(src.dotted, ()):
            return
        guard = self.ctx.module_guards.get(src.dotted, {}).get(name)
        if guard is not None and guard in held:
            return
        if guard is None and held:
            return  # generic lint: any lock held counts
        if self.is_reset or self._suppressed(line, "unlocked-ok"):
            return
        self.findings.append(Finding(
            "unlocked-global-write", self.mod.file, line,
            "mutable module global %s.%s written in %s without holding %s"
            % (src.stem, name, self.fi.qualname,
               repr(guard) if guard else "a lock")))

    def _check_guarded_access(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        # self.X loads/stores against class guards
        if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
                and node.value.id == "self" and self.ci is not None):
            guard = self.ctx.class_guards.get(
                (self.mod.dotted, self.ci.name), {}).get(node.attr)
            if guard is None or guard in held:
                return
            if self.is_init or self.is_locked_conv:
                return
            if self._suppressed(node.lineno, "unguarded-ok"):
                return
            self.findings.append(Finding(
                "guarded-field", self.mod.file, node.lineno,
                "%s accesses self.%s without holding its declared guard %r"
                % (self.fi.qualname, node.attr, guard)))
            return
        # module-global guarded reads/writes (same module or via alias)
        ref = self._global_ref(node) if isinstance(node, (ast.Name, ast.Attribute)) else None
        if ref is None:
            return
        src, name = ref
        guard = self.ctx.module_guards.get(src.dotted, {}).get(name)
        if guard is None or guard in held:
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            return  # plain rebind is atomic; the mutation lint covers the rest
        if self.is_init or self.is_locked_conv or self.is_reset:
            return
        if self._suppressed(node.lineno, "unguarded-ok"):
            return
        self.findings.append(Finding(
            "guarded-field", self.mod.file, node.lineno,
            "%s accesses %s.%s without holding its declared guard %r"
            % (self.fi.qualname, src.stem, name, guard)))

    # -- traversal --
    def run(self) -> None:
        node = self.fi.node
        self._walk(getattr(node, "body", []), ())

    def _walk(self, stmts, held: Tuple[str, ...]) -> None:
        for st in stmts:
            self._walk_stmt(st, held)

    def _walk_stmt(self, st: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(st, ast.With):
            new_held = held
            for item in st.items:
                self._check_expr(item.context_expr, held)
                ld = self.res.resolve_lock(self.mod, self.fi.cls, item.context_expr)
                if ld is not None:
                    new_held = new_held + (ld.lock_id,)
            self._walk(st.body, new_held)
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested = _FuncInfo("%s.<locals>.%s" % (self.fi.qualname, st.name),
                               self.fi.file, st, self.fi.cls, self.fi.module)
            _StateWalker(self.res, self.mod, self.ci, nested, self.ctx,
                         self.findings).run()
            return
        if isinstance(st, ast.Assign):
            for t in st.targets:
                self._check_target(t, held)
            self._check_expr(st.value, held)
            return
        if isinstance(st, ast.AugAssign):
            self._check_target(st.target, held, aug=True)
            self._check_expr(st.value, held)
            return
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._check_target(st.target, held)
                self._check_expr(st.value, held)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                self._check_target(t, held)
            return
        for _f, value in ast.iter_fields(st):
            if isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.stmt):
                        self._walk_stmt(v, held)
                    elif isinstance(v, ast.excepthandler):
                        if v.type is not None:
                            self._check_expr(v.type, held)
                        self._walk(v.body, held)
                    elif isinstance(v, ast.AST):
                        self._check_expr(v, held)
            elif isinstance(value, ast.AST):
                self._check_expr(value, held)

    def _check_target(self, t: ast.AST, held: Tuple[str, ...], aug: bool = False) -> None:
        if isinstance(t, ast.Subscript):
            self._check_mutation(t.value, held, t.lineno)
            self._check_guarded_access(t.value, held)
            self._check_expr(t.slice, held)
        elif isinstance(t, ast.Name):
            if aug:
                self._check_mutation(t, held, t.lineno)
            self._check_guarded_access(t, held)
        elif isinstance(t, ast.Attribute):
            self._check_guarded_access(t, held)
            if aug:
                self._check_mutation(t, held, t.lineno)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._check_target(el, held)

    def _check_expr(self, expr: ast.AST, held: Tuple[str, ...]) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                    self._check_mutation(f.value, held, node.lineno)
            elif isinstance(node, (ast.Name, ast.Attribute)):
                self._check_guarded_access(node, held)


class _StateContext:
    def __init__(self, result: LockGraphResult):
        self.mutable_globals: Dict[str, Dict[str, int]] = {}
        self.module_guards: Dict[str, Dict[str, str]] = {}
        self.class_guards: Dict[Tuple[str, str], Dict[str, str]] = {}
        for dotted, mod in result.modules.items():
            self.mutable_globals[dotted] = _mutable_globals(mod)
            self.module_guards[dotted] = _module_guards(mod)
            for ci in mod.classes.values():
                g = _class_guards(mod, ci)
                if g:
                    self.class_guards[(dotted, ci.name)] = g


def _scan_threads(mod: _ModuleInfo, findings: List[Finding]) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        is_thread = (
            (isinstance(f, ast.Attribute) and f.attr == "Thread"
             and isinstance(f.value, ast.Name)
             and f.value.id in ("threading", "_threading"))
            or (isinstance(f, ast.Name) and f.id == "Thread"
                and mod.import_names.get("Thread", ("", ""))[0] == "threading")
        )
        if is_thread and not _line_has(mod, node.lineno, "thread-ok"):
            findings.append(Finding(
                "unregistered-thread", mod.file, node.lineno,
                "direct threading.Thread(...) spawn bypasses "
                "analysis.threadreg — use threadreg.spawn(name, target, "
                "owner=...) so the thread is named and leak-checked"))


def scan_shared_state(result: LockGraphResult) -> List[Finding]:
    """Run the shared-state checks over an already-parsed lock graph."""
    findings: List[Finding] = []
    ctx = _StateContext(result)
    res = result.resolver
    for dotted, mod in sorted(result.modules.items()):
        _scan_threads(mod, findings)
        funcs: List[Tuple[Optional[_ClassInfo], _FuncInfo]] = [
            (None, fi) for fi in mod.functions.values()]
        for ci in mod.classes.values():
            funcs.extend((ci, fi) for fi in ci.methods.values())
        for ci, fi in funcs:
            _StateWalker(res, mod, ci, fi, ctx, findings).run()
    return findings
