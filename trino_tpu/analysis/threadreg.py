"""Thread sanitizer: a registry every background spawn site goes through.

The engine spawns ~19 kinds of background threads (warmup, fabric push,
watchdogs, heartbeat, query tracker, HTTP servers, exchange pull loops,
chaos populations, ...).  Spawning through :func:`spawn` gives each one
a stable name and an owner, so

* leaks become *named* failures: the tier-1 autouse fixture calls
  :func:`non_daemon_leaks` / :func:`live` after every module;
* :func:`join_all` gives services a uniform teardown with a deadline;
* the static pass (``analysis.shared_state``) flags any direct
  ``threading.Thread(...)`` call in the package that bypasses this
  module, keeping the inventory complete by construction.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["ThreadRegistry", "THREADS", "spawn"]


class _Record:
    __slots__ = ("ref", "name", "owner", "long_lived")

    def __init__(self, thread: threading.Thread, name: str, owner: str,
                 long_lived: bool = False):
        self.ref = weakref.ref(thread)
        self.name = name
        self.owner = owner
        self.long_lived = long_lived


class ThreadRegistry:
    """Named ownership for every background thread the engine spawns."""

    def __init__(self):
        # Deliberately a plain lock: the registry is a leaf the witness
        # itself may sit above, and it must work before analysis.witness
        # is configured.
        self._lock = threading.Lock()
        self._records: List[_Record] = []
        self.spawned_total = 0

    def spawn(self, name: str, target: Callable, *, args: Tuple = (),
              kwargs: Optional[dict] = None, daemon: bool = True,
              owner: str = "", start: bool = True) -> threading.Thread:
        t = threading.Thread(  # thread-ok: the registry is the one sanctioned spawn site
            target=target, name=name, args=args, kwargs=kwargs or {},
            daemon=daemon,
        )
        self.register(t, name=name, owner=owner)
        if start:
            t.start()
        return t

    def register(self, thread: threading.Thread, *, name: Optional[str] = None,
                 owner: str = "", long_lived: bool = False) -> threading.Thread:
        """Adopt an externally-created thread into the registry.

        `long_lived=True` marks a sanctioned process-lifetime worker (a
        lazily-built singleton pool whose threads cannot be daemons,
        e.g. ThreadPoolExecutor workers): it stays visible in `live()`
        but is not reported by `non_daemon_leaks`."""
        with self._lock:
            self._prune_locked()
            self._records.append(
                _Record(thread, name or thread.name, owner, long_lived))
            self.spawned_total += 1
        return thread

    def adopt_current(self, *, owner: str = "",
                      long_lived: bool = False) -> threading.Thread:
        """Register the calling thread (pool-initializer idiom)."""
        return self.register(threading.current_thread(), owner=owner,
                             long_lived=long_lived)

    def _prune_locked(self) -> None:
        self._records = [
            r for r in self._records
            if r.ref() is not None and (r.ref().is_alive() or not r.ref().ident)
        ]

    def live(self) -> List[Tuple[str, str, bool]]:
        """(name, owner, daemon) for every registered thread still alive."""
        out = []
        with self._lock:
            for r in self._records:
                t = r.ref()
                if t is not None and t.is_alive():
                    out.append((r.name, r.owner, t.daemon))
        return out

    def live_count(self) -> int:
        return len(self.live())

    def non_daemon_leaks(self) -> List[str]:
        """Alive non-daemon threads other than main/pytest internals.

        Covers *all* threads, registered or not, so a spawn site that
        dodged the registry still shows up — just without an owner.
        """
        known: Dict[int, _Record] = {}
        with self._lock:
            for r in self._records:
                t = r.ref()
                if t is not None and t.ident is not None:
                    known[t.ident] = r
        leaks = []
        main = threading.main_thread()
        for t in threading.enumerate():
            if t is main or t.daemon or not t.is_alive():
                continue
            if t.__class__.__name__ == "_DummyThread":
                continue
            rec = known.get(t.ident)
            if rec is not None:
                if rec.long_lived:
                    continue
                leaks.append("%s (owner=%s)" % (rec.name, rec.owner or "?"))
            else:
                leaks.append("%s (UNREGISTERED)" % (t.name,))
        return leaks

    def join_all(self, timeout: float = 5.0, owner: Optional[str] = None) -> List[str]:
        """Join registered threads (optionally one owner's); returns the
        names of threads still alive at the deadline."""
        deadline = time.monotonic() + timeout
        stragglers = []
        with self._lock:
            records = list(self._records)
        for r in records:
            t = r.ref()
            if t is None or not t.is_alive():
                continue
            if owner is not None and r.owner != owner:
                continue
            t.join(max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                stragglers.append("%s (owner=%s)" % (r.name, r.owner or "?"))
        with self._lock:
            self._prune_locked()
        return stragglers


THREADS = ThreadRegistry()


def spawn(name: str, target: Callable, *, args: Tuple = (),
          kwargs: Optional[dict] = None, daemon: bool = True,
          owner: str = "", start: bool = True) -> threading.Thread:
    """Module-level convenience over the process registry."""
    return THREADS.spawn(name, target, args=args, kwargs=kwargs,
                         daemon=daemon, owner=owner, start=start)
