"""Runtime lock witness: named locks with dynamic order checking.

Every lock in the engine is created through :func:`named_lock`,
:func:`named_rlock` or :func:`named_condition` so it carries a stable
name ("MeshScheduler._lock", "warmup._warm_lock", ...).  When the
witness is enabled (env ``TRINO_TPU_LOCK_WITNESS=1``, and by default
under pytest) each acquisition is checked against the partial order
observed so far, in the style of the FreeBSD WITNESS checker and the
lockdep family:

* the first time lock B is acquired while A is held, the edge A -> B is
  recorded together with both call sites;
* a later acquisition of A while B is held contradicts the recorded
  order and raises :class:`LockOrderError` naming both locks and both
  stacks;
* same-thread re-entry on a non-reentrant lock raises immediately
  instead of deadlocking silently.

The static pass (``analysis.lockgraph``) derives the same graph from
the source; :func:`seed_order` lets callers pre-load those edges so the
dynamic checker starts from the statically-derived partial order rather
than first-observation order.

When the witness is disabled the wrappers degrade to a flag check plus
owner bookkeeping (needed for ``Condition._is_owned``); no stacks are
captured and no edges are recorded.
"""

from __future__ import annotations

import os
import sys
import threading
import weakref
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = [
    "LockOrderError",
    "named_lock",
    "named_rlock",
    "named_condition",
    "witness_enabled",
    "enable_witness",
    "held_locks",
    "lock_count",
    "order_edge_count",
    "violation_count",
    "seed_order",
    "reset_witness_for_tests",
]


class LockOrderError(RuntimeError):
    """A lock acquisition contradicts the witnessed partial order.

    Carries the two lock names plus the call sites that established the
    conflicting order, so the report names both locks and both stacks.
    """

    def __init__(self, message: str, *, lock_a: str, lock_b: str,
                 stack_a: Tuple[str, ...] = (), stack_b: Tuple[str, ...] = ()):
        super().__init__(message)
        self.lock_a = lock_a
        self.lock_b = lock_b
        self.stack_a = stack_a
        self.stack_b = stack_b


def _default_enabled() -> bool:
    v = os.environ.get("TRINO_TPU_LOCK_WITNESS")
    if v is not None:
        return v.strip().lower() not in ("", "0", "false", "no", "off")
    return "pytest" in sys.modules or "PYTEST_CURRENT_TEST" in os.environ


_ENABLED = _default_enabled()

# -- global witness state -------------------------------------------------
# _succ holds the observed partial order: name -> set of names acquired
# while it was held.  _edge_site remembers the (hold, acquire) call sites
# that first established each edge so violations can print both stacks.
_order_mu = threading.Lock()
_succ: Dict[str, Set[str]] = {}
_edge_site: Dict[Tuple[str, str], Tuple[Tuple[str, ...], Tuple[str, ...]]] = {}
_violations = 0
_registry: "weakref.WeakSet" = weakref.WeakSet()

_tls = threading.local()
# ident -> the same list object stored in that thread's TLS, for the
# cross-thread held_locks() snapshot used by the leak fixture.
_all_held: Dict[int, List[Tuple[object, str, Tuple[str, ...]]]] = {}

_SELF_FILE = __file__
_THREADING_FILE = threading.__file__


def witness_enabled() -> bool:
    return _ENABLED


def enable_witness(on: bool = True) -> None:
    """Flip the witness at runtime (used by bench --chaos-smoke)."""
    global _ENABLED
    _ENABLED = bool(on)


def _held() -> List[Tuple[object, str, Tuple[str, ...]]]:
    try:
        return _tls.held
    except AttributeError:
        lst: List[Tuple[object, str, Tuple[str, ...]]] = []
        _tls.held = lst
        _all_held[threading.get_ident()] = lst  # unlocked-ok: thread-own key, GIL-atomic setitem
        return lst


def _callsite(limit: int = 3) -> Tuple[Tuple[str, int, str], ...]:
    """Cheap stack summary: up to `limit` frames outside witness/threading.

    Returns raw (filename, lineno, co_name) tuples — this runs on every
    enabled acquire, so string formatting is deferred to _site_str,
    which only runs when building an error message."""
    frames: List[Tuple[str, int, str]] = []
    f = sys._getframe(1)
    while f is not None and len(frames) < limit:
        code = f.f_code
        fn = code.co_filename
        if fn != _SELF_FILE and fn != _THREADING_FILE:
            frames.append((fn, f.f_lineno, code.co_name))
        f = f.f_back
    return tuple(frames)


def _site_str(site: Tuple) -> str:
    return " | ".join("%s:%d in %s" % frame for frame in site)


def _path_between(src: str, dst: str) -> Optional[List[str]]:
    """DFS over _succ; caller holds _order_mu."""
    if src == dst:
        return [src]
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in _succ.get(node, ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _record_violation() -> None:
    global _violations
    _violations += 1


def _check_order(acquiring_name: str, acq_site: Tuple[str, ...]) -> None:
    """Record edges held->acquiring; raise if the reverse order exists."""
    held = _held()
    if not held:
        return
    for _lk, hname, hsite in held:
        if hname == acquiring_name:
            # Distinct instances sharing a name (per-replica locks): no
            # instance-level order is defined, so skip; true re-entry on
            # the same instance is caught before this point.
            continue
        succ = _succ.get(hname)
        if succ is not None and acquiring_name in succ:
            continue  # edge already known, fast path
        with _order_mu:
            succ = _succ.get(hname)
            if succ is not None and acquiring_name in succ:
                continue
            rev = _path_between(acquiring_name, hname)
            if rev is not None:
                first_edge = (rev[0], rev[1]) if len(rev) > 1 else (rev[0], rev[0])
                prior = _edge_site.get(first_edge, ((), ()))
                _record_violation()
                raise LockOrderError(
                    "lock order violation: acquiring %r while holding %r, "
                    "but the reverse order %s was already witnessed\n"
                    "  held %r at: %s\n"
                    "  acquiring %r at: %s\n"
                    "  prior edge %s -> %s established holding at %s, "
                    "acquiring at %s"
                    % (
                        acquiring_name, hname, " -> ".join(rev),
                        hname, _site_str(hsite) or "<unknown>",
                        acquiring_name, _site_str(acq_site) or "<unknown>",
                        first_edge[0], first_edge[1],
                        _site_str(prior[0]) or "<static>",
                        _site_str(prior[1]) or "<static>",
                    ),
                    lock_a=hname, lock_b=acquiring_name,
                    stack_a=hsite, stack_b=acq_site,
                )
            _succ.setdefault(hname, set()).add(acquiring_name)
            _edge_site.setdefault((hname, acquiring_name), (hsite, acq_site))


class _WitnessLock:
    """Non-reentrant named lock; witness-checked when enabled."""

    reentrant = False

    def __init__(self, name: str):
        self.name = name
        self._inner = threading.Lock()
        self._owner = 0
        _registry.add(self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        site: Tuple[str, ...] = ()
        if _ENABLED and blocking:
            if self._owner == me:
                site = _callsite()
                _record_violation()
                raise LockOrderError(
                    "non-reentrant re-entry: thread %d already holds %r, "
                    "re-acquiring at: %s" % (me, self.name, _site_str(site)),
                    lock_a=self.name, lock_b=self.name,
                    stack_a=self._held_site(), stack_b=site,
                )
            site = _callsite()
            _check_order(self.name, site)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._owner = me
            if _ENABLED:
                _held().append((self, self.name, site))
        return ok

    def release(self) -> None:
        self._owner = 0
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                del held[i]
                break
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _held_site(self) -> Tuple[str, ...]:
        for lk, _name, site in _held():
            if lk is self:
                return site
        return ()

    # threading.Condition protocol
    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return "<WitnessLock %s owner=%d>" % (self.name, self._owner)


class _WitnessRLock:
    """Reentrant named lock; supports the Condition save/restore protocol."""

    reentrant = True

    def __init__(self, name: str):
        self.name = name
        self._inner = threading.RLock()
        self._owner = 0
        self._count = 0
        _registry.add(self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        first = self._owner != me
        site: Tuple[str, ...] = ()
        if _ENABLED and blocking and first:
            site = _callsite()
            _check_order(self.name, site)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if first:
                self._owner = me
                self._count = 1
                if _ENABLED:
                    _held().append((self, self.name, site))
            else:
                self._count += 1
        return ok

    def release(self) -> None:
        if self._owner == threading.get_ident():
            self._count -= 1
            if self._count <= 0:
                self._owner = 0
                self._count = 0
                self._drop_held()
        self._inner.release()

    def _drop_held(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                del held[i]
                break

    # threading.Condition protocol: wait() fully releases the recursion
    # and restores it on wake.
    def _release_save(self):
        count = self._count
        self._owner = 0
        self._count = 0
        self._drop_held()
        return (self._inner._release_save(), count)

    def _acquire_restore(self, saved) -> None:
        inner_state, count = saved
        self._inner._acquire_restore(inner_state)
        self._owner = threading.get_ident()
        self._count = count
        if _ENABLED:
            _held().append((self, self.name, _callsite()))

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return "<WitnessRLock %s owner=%d count=%d>" % (
            self.name, self._owner, self._count)


def named_lock(name: str) -> _WitnessLock:
    """A non-reentrant lock registered with the witness under `name`."""
    return _WitnessLock(name)


def named_rlock(name: str) -> _WitnessRLock:
    """A reentrant lock registered with the witness under `name`."""
    return _WitnessRLock(name)


def named_condition(name: str, lock=None) -> threading.Condition:
    """A Condition over a witness lock (reentrant when lock is omitted,
    matching threading.Condition's own default of RLock)."""
    return threading.Condition(lock if lock is not None else named_rlock(name))


# -- introspection --------------------------------------------------------

def held_locks() -> List[str]:
    """Names of all witness locks currently held by any thread."""
    out: List[str] = []
    for lst in list(_all_held.values()):
        out.extend(name for _lk, name, _site in list(lst))
    return out


def lock_count() -> int:
    return len(_registry)


def order_edge_count() -> int:
    with _order_mu:
        return sum(len(s) for s in _succ.values())


def violation_count() -> int:
    return _violations


def seed_order(edges: Iterable[Tuple[str, str]]) -> int:
    """Pre-load statically-derived order edges; returns edges added."""
    added = 0
    with _order_mu:
        for a, b in edges:
            if a == b:
                continue
            if _path_between(b, a) is not None:
                continue  # never seed a contradiction
            succ = _succ.setdefault(a, set())
            if b not in succ:
                succ.add(b)
                added += 1
    return added


def reset_witness_for_tests() -> None:
    """Clear the observed order and counters (unit tests only)."""
    global _violations
    with _order_mu:
        _succ.clear()
        _edge_site.clear()
    _violations = 0
