"""Concurrency soundness plane.

Three cooperating layers over the engine's ~50 locks and ~19 background
thread spawn sites:

* :mod:`trino_tpu.analysis.lockgraph` — static AST pass: every lock
  acquisition site attributed to a named lock, the
  may-hold-while-acquiring graph across call edges, cycle findings with
  file:line witness paths.
* :mod:`trino_tpu.analysis.shared_state` — static lint: unlocked
  mutable-global writes, the ``# guarded_by:`` field convention, and
  raw ``threading.Thread`` spawns that bypass the registry.
* :mod:`trino_tpu.analysis.witness` / :mod:`~.threadreg` — the dynamic
  half: named-lock order witness (on under pytest) and the thread
  registry the leak fixture drains.

``bench.py --analyze`` runs the static passes as a CI gate;
:func:`analyze_package` is its engine and is also what the tier-1
clean-tree test asserts on.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from trino_tpu.analysis.lockgraph import (
    Finding, LockGraphResult, PACKAGE_ROOT, scan_sources,
)
from trino_tpu.analysis.shared_state import scan_shared_state
from trino_tpu.analysis.witness import (
    LockOrderError, enable_witness, held_locks, lock_count, named_condition,
    named_lock, named_rlock, order_edge_count, seed_order, violation_count,
    witness_enabled,
)
from trino_tpu.analysis import threadreg
from trino_tpu.analysis.threadreg import THREADS, spawn

__all__ = [
    "Finding", "LockOrderError", "AnalysisReport",
    "analyze_package", "analyze_sources",
    "named_lock", "named_rlock", "named_condition", "spawn", "THREADS",
    "witness_enabled", "enable_witness", "seed_order",
    "concurrency_summary", "register_analysis_metrics",
]

_VIOLATION_KINDS = (
    "lock-cycle", "lock-reentry", "wait-while-holding",
    "unlocked-global-write", "guarded-field", "unregistered-thread",
)


@dataclass
class AnalysisReport:
    """Combined result of the static passes."""

    graph: LockGraphResult
    findings: List[Finding] = field(default_factory=list)
    files: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings

    def by_kind(self) -> Dict[str, int]:
        out = {k: 0 for k in _VIOLATION_KINDS}
        for f in self.findings:
            out[f.kind] = out.get(f.kind, 0) + 1
        return out

    def summary(self) -> Dict[str, object]:
        kinds = self.by_kind()
        return {
            "files": self.files,
            "locks": len(self.graph.locks),
            "sites": self.graph.sites,
            "edges": len(self.graph.edges),
            "cycles": kinds["lock-cycle"],
            "reentry": kinds["lock-reentry"],
            "wait_while_holding": kinds["wait-while-holding"],
            "unlocked_global_writes": kinds["unlocked-global-write"],
            "guarded_field_violations": kinds["guarded-field"],
            "unregistered_threads": kinds["unregistered-thread"],
            "violations": len(self.findings),
            "ok": self.ok,
        }


def _package_sources(root: Optional[str] = None) -> Dict[str, Tuple[str, str]]:
    root = root or PACKAGE_ROOT
    pkg_parent = os.path.dirname(root)
    sources: Dict[str, Tuple[str, str]] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, pkg_parent)
            dotted = rel[:-3].replace(os.sep, ".")
            if dotted.endswith(".__init__"):
                dotted = dotted[: -len(".__init__")]
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    text = fh.read()
            except OSError:
                continue
            sources[dotted] = (os.path.relpath(path, os.getcwd())
                               if path.startswith(os.getcwd()) else path, text)
    return sources


def analyze_sources(sources: Dict[str, Tuple[str, str]]) -> AnalysisReport:
    """Static passes over in-memory sources: dotted name -> (path, text)."""
    graph = scan_sources(sources)
    findings = list(graph.findings)
    findings.extend(scan_shared_state(graph))
    findings.sort(key=lambda f: (f.file, f.line, f.kind))
    return AnalysisReport(graph=graph, findings=findings, files=len(sources))


def analyze_package(root: Optional[str] = None) -> AnalysisReport:
    """Static passes over the installed package tree (or `root`)."""
    return analyze_sources(_package_sources(root))


# -- runtime inventory ----------------------------------------------------

def concurrency_summary() -> Dict[str, object]:
    """Live witness/thread inventory for metrics and EXPLAIN ANALYZE."""
    return {
        "locks": lock_count(),
        "held": len(held_locks()),
        "order_edges": order_edge_count(),
        "threads_live": THREADS.live_count(),
        "threads_spawned": THREADS.spawned_total,
        "witness": int(witness_enabled()),
        "witness_violations": violation_count(),
    }


def register_analysis_metrics(registry=None) -> None:
    """Expose analysis.{locks,threads_live,witness_violations} gauges."""
    if registry is None:
        from trino_tpu.runtime.metrics import METRICS as registry
    registry.register_gauge("analysis.locks", lock_count)
    registry.register_gauge("analysis.threads_live", THREADS.live_count)
    registry.register_gauge("analysis.witness_violations", violation_count)
