"""Static lock-order analysis over the package source.

An AST pass that

1. finds every lock *definition* — ``threading.Lock()`` / ``RLock()`` /
   ``Condition()`` assignments plus the witness factories
   ``named_lock("...")`` / ``named_rlock`` / ``named_condition`` — and
   gives each a stable id (the witness name literal when present, else
   ``Class.attr`` / ``module.attr``);
2. extracts every acquisition site: ``with self._lock:``, raw
   ``.acquire()`` calls, and ``Condition.wait`` re-acquisitions,
   attributed to a lock definition through a light resolver (self
   attributes, module globals, imported module attributes, module-level
   singletons of known classes, ``self.attr`` instance types);
3. builds the may-hold-while-acquiring graph across call edges (a
   fixpoint of locks-a-function-may-acquire propagated through resolved
   calls), and
4. reports every cycle as a potential deadlock, printing for each edge
   in the cycle the witness path file:line chain.

The resolver is deliberately conservative: an unresolved receiver
produces no lock event and no call edge, so the graph under-approximates
rather than hallucinating edges.  Findings it does produce name a
concrete construct at a concrete file:line.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "LockDef", "LockGraphResult", "scan_sources", "PACKAGE_ROOT"]

PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_LOCK_CTORS = {"Lock", "RLock"}
_WITNESS_FACTORIES = {"named_lock": False, "named_rlock": True, "named_condition": True}


@dataclass
class Finding:
    kind: str
    file: str
    line: int
    message: str

    def __str__(self) -> str:
        return "[%s] %s:%d %s" % (self.kind, self.file, self.line, self.message)


@dataclass
class LockDef:
    lock_id: str
    file: str
    line: int
    reentrant: bool = False


@dataclass
class _FuncInfo:
    qualname: str          # "mod::Class.method" or "mod::func"
    file: str
    node: ast.AST
    cls: Optional[str]     # owning class name, if any
    module: str            # dotted module key


@dataclass
class _ClassInfo:
    name: str
    module: str
    bases: List[str] = field(default_factory=list)
    methods: Dict[str, _FuncInfo] = field(default_factory=dict)
    lock_attrs: Dict[str, LockDef] = field(default_factory=dict)
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> "mod::Class"


@dataclass
class _ModuleInfo:
    dotted: str            # e.g. "trino_tpu.runtime.scheduler"
    stem: str              # "scheduler"
    file: str              # display path
    tree: ast.Module = None
    lines: List[str] = field(default_factory=list)
    classes: Dict[str, _ClassInfo] = field(default_factory=dict)
    functions: Dict[str, _FuncInfo] = field(default_factory=dict)
    locks: Dict[str, LockDef] = field(default_factory=dict)       # global name -> def
    singletons: Dict[str, str] = field(default_factory=dict)      # name -> "mod::Class"
    import_mods: Dict[str, str] = field(default_factory=dict)     # alias -> dotted
    import_names: Dict[str, Tuple[str, str]] = field(default_factory=dict)  # alias -> (dotted, name)


@dataclass
class _Event:
    kind: str                      # "acquire" | "call" | "wait"
    target: str                    # lock_id or callee qualname
    held: Tuple[str, ...]          # lock ids lexically held
    file: str
    line: int
    func: str


@dataclass
class LockGraphResult:
    locks: Dict[str, LockDef] = field(default_factory=dict)
    sites: int = 0
    edges: Dict[Tuple[str, str], List[str]] = field(default_factory=dict)
    cycles: List[List[str]] = field(default_factory=list)
    findings: List[Finding] = field(default_factory=list)
    modules: Dict[str, "_ModuleInfo"] = field(default_factory=dict)
    events: Dict[str, List[_Event]] = field(default_factory=dict)
    resolver: Optional["_Resolver"] = None

    def order_pairs(self) -> List[Tuple[str, str]]:
        return sorted(self.edges.keys())


def _line_has(mod: _ModuleInfo, line: int, marker: str) -> bool:
    if 1 <= line <= len(mod.lines):
        return marker in mod.lines[line - 1]
    return False


def _call_name(node: ast.AST) -> Optional[str]:
    """'Lock' for threading.Lock() / Lock(); 'named_lock' for witness.named_lock()."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _literal_str_arg(node: ast.Call) -> Optional[str]:
    if node.args and isinstance(node.args[0], ast.Constant) and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def _lockdef_from_value(value: ast.AST, default_id: str, file: str) -> Optional[LockDef]:
    """A LockDef if `value` constructs a lock/condition, else None."""
    name = _call_name(value)
    if name is None:
        return None
    if name in _LOCK_CTORS:
        return LockDef(default_id, file, value.lineno, reentrant=(name == "RLock"))
    if name in _WITNESS_FACTORIES:
        lit = _literal_str_arg(value)
        return LockDef(lit or default_id, file, value.lineno,
                       reentrant=_WITNESS_FACTORIES[name])
    if name == "Condition":
        # bare Condition() owns a private RLock; Condition(x) aliases x
        # and is handled by the caller (needs the resolver).
        if not value.args:
            return LockDef(default_id, file, value.lineno, reentrant=True)
    return None


# -- phase A: per-module symbol collection --------------------------------

def _collect_module(dotted: str, file: str, source: str) -> Optional[_ModuleInfo]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    mod = _ModuleInfo(dotted=dotted, stem=dotted.rsplit(".", 1)[-1], file=file,
                      tree=tree, lines=source.splitlines())

    for node in tree.body:
        if isinstance(node, ast.Import):
            for a in node.names:
                mod.import_mods[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            src = _resolve_from_import(dotted, node)
            if src is not None:
                for a in node.names:
                    if a.name == "*":
                        continue
                    mod.import_names[a.asname or a.name] = (src, a.name)
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            value = node.value
            if value is None or len(targets) != 1 or not isinstance(targets[0], ast.Name):
                continue
            name = targets[0].id
            ld = _lockdef_from_value(value, "%s.%s" % (mod.stem, name), file)
            if ld is not None:
                mod.locks[name] = ld
            elif isinstance(value, ast.Call):
                ctor = _call_name(value)
                if ctor and ctor[:1].isupper():
                    mod.singletons[name] = ctor  # resolved to a class later
        elif isinstance(node, ast.ClassDef):
            ci = _ClassInfo(name=node.name, module=dotted,
                            bases=[b.id for b in node.bases if isinstance(b, ast.Name)])
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fi = _FuncInfo("%s::%s.%s" % (dotted, node.name, item.name),
                                   file, item, node.name, dotted)
                    ci.methods[item.name] = fi
            _collect_self_attrs(ci, file)
            mod.classes[node.name] = ci
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.functions[node.name] = _FuncInfo("%s::%s" % (dotted, node.name),
                                                 file, node, None, dotted)
    return mod


def _resolve_from_import(dotted: str, node: ast.ImportFrom) -> Optional[str]:
    if node.level == 0:
        return node.module
    parts = dotted.split(".")
    if node.level > len(parts):
        return None
    base = parts[: len(parts) - node.level]
    if node.module:
        base.append(node.module)
    return ".".join(base)


def _collect_self_attrs(ci: _ClassInfo, file: str) -> None:
    """Scan all methods for self.X = Lock()/ClassName()/Condition(self.Y)."""
    for fi in ci.methods.values():
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            attr = t.attr
            default_id = "%s.%s" % (ci.name, attr)
            ld = _lockdef_from_value(node.value, default_id, file)
            if ld is not None:
                ci.lock_attrs.setdefault(attr, ld)
                continue
            if isinstance(node.value, ast.Call):
                cname = _call_name(node.value)
                if cname == "Condition" and node.value.args:
                    arg = node.value.args[0]
                    if (isinstance(arg, ast.Attribute) and isinstance(arg.value, ast.Name)
                            and arg.value.id == "self" and arg.attr in ci.lock_attrs):
                        ci.lock_attrs.setdefault(attr, ci.lock_attrs[arg.attr])
                elif cname == "named_condition":
                    lit = _literal_str_arg(node.value)
                    ci.lock_attrs.setdefault(attr, LockDef(
                        lit or default_id, file, node.value.lineno, reentrant=True))
                elif cname and cname[:1].isupper():
                    ci.attr_types.setdefault(attr, cname)


# -- phase B: resolution + event extraction -------------------------------

class _Resolver:
    def __init__(self, modules: Dict[str, _ModuleInfo]):
        self.modules = modules
        # resolve singleton ctor names and self-attr types to classes
        for mod in modules.values():
            for name, ctor in list(mod.singletons.items()):
                ref = self._class_ref(mod, ctor)
                if ref is None:
                    del mod.singletons[name]
                else:
                    mod.singletons[name] = ref
            for ci in mod.classes.values():
                for attr, ctor in list(ci.attr_types.items()):
                    ref = self._class_ref(mod, ctor)
                    if ref is None:
                        del ci.attr_types[attr]
                    else:
                        ci.attr_types[attr] = ref

    def _class_ref(self, mod: _ModuleInfo, name: str) -> Optional[str]:
        if name in mod.classes:
            return "%s::%s" % (mod.dotted, name)
        imp = mod.import_names.get(name)
        if imp is not None:
            src = self.modules.get(imp[0])
            if src is not None and imp[1] in src.classes:
                return "%s::%s" % (imp[0], imp[1])
        return None

    def class_info(self, ref: str) -> Optional[_ClassInfo]:
        dotted, _, cname = ref.partition("::")
        m = self.modules.get(dotted)
        return m.classes.get(cname) if m else None

    def method(self, ref: str, name: str, depth: int = 0) -> Optional[_FuncInfo]:
        ci = self.class_info(ref)
        if ci is None or depth > 4:
            return None
        if name in ci.methods:
            return ci.methods[name]
        m = self.modules.get(ci.module)
        for base in ci.bases:
            base_ref = self._class_ref(m, base) if m else None
            if base_ref:
                fi = self.method(base_ref, name, depth + 1)
                if fi is not None:
                    return fi
        return None

    def lock_attr(self, ref: str, attr: str, depth: int = 0) -> Optional[LockDef]:
        ci = self.class_info(ref)
        if ci is None or depth > 4:
            return None
        if attr in ci.lock_attrs:
            return ci.lock_attrs[attr]
        m = self.modules.get(ci.module)
        for base in ci.bases:
            base_ref = self._class_ref(m, base) if m else None
            if base_ref:
                ld = self.lock_attr(base_ref, attr, depth + 1)
                if ld is not None:
                    return ld
        return None

    def resolve_lock(self, mod: _ModuleInfo, cls: Optional[str],
                     expr: ast.AST) -> Optional[LockDef]:
        """Resolve an expression to a LockDef, or None."""
        if isinstance(expr, ast.Name):
            if expr.id in mod.locks:
                return mod.locks[expr.id]
            imp = mod.import_names.get(expr.id)
            if imp is not None:
                src = self.modules.get(imp[0])
                if src is not None and imp[1] in src.locks:
                    return src.locks[imp[1]]
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id in ("self", "cls") and cls is not None:
                    return self.lock_attr("%s::%s" % (mod.dotted, cls), expr.attr)
                # module alias: fabric_mod._fabric_lock
                alias = mod.import_mods.get(base.id)
                if alias is not None:
                    src = self.modules.get(alias)
                    if src is not None and expr.attr in src.locks:
                        return src.locks[expr.attr]
                # singleton attr: METRICS._lock
                ref = self._singleton_ref(mod, base.id)
                if ref is not None:
                    return self.lock_attr(ref, expr.attr)
            elif (isinstance(base, ast.Attribute) and isinstance(base.value, ast.Name)
                  and base.value.id in ("self", "cls") and cls is not None):
                # self.attr._lock where self.attr has a known class type
                ci = self.class_info("%s::%s" % (mod.dotted, cls))
                if ci is not None:
                    ref = ci.attr_types.get(base.attr)
                    if ref is not None:
                        return self.lock_attr(ref, expr.attr)
        return None

    def _singleton_ref(self, mod: _ModuleInfo, name: str) -> Optional[str]:
        if name in mod.singletons:
            return mod.singletons[name]
        imp = mod.import_names.get(name)
        if imp is not None:
            src = self.modules.get(imp[0])
            if src is not None and imp[1] in src.singletons:
                return src.singletons[imp[1]]
        return None

    def resolve_call(self, mod: _ModuleInfo, cls: Optional[str],
                     node: ast.Call) -> Optional[_FuncInfo]:
        f = node.func
        if isinstance(f, ast.Name):
            name = f.id
            if name in mod.functions:
                return mod.functions[name]
            if name in mod.classes:
                return mod.classes[name].methods.get("__init__")
            imp = mod.import_names.get(name)
            if imp is not None:
                src = self.modules.get(imp[0])
                if src is not None:
                    if imp[1] in src.functions:
                        return src.functions[imp[1]]
                    if imp[1] in src.classes:
                        return src.classes[imp[1]].methods.get("__init__")
            return None
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name):
                if base.id in ("self", "cls") and cls is not None:
                    return self.method("%s::%s" % (mod.dotted, cls), f.attr)
                alias = mod.import_mods.get(base.id)
                if alias is not None:
                    src = self.modules.get(alias)
                    if src is not None:
                        if f.attr in src.functions:
                            return src.functions[f.attr]
                        if f.attr in src.classes:
                            return src.classes[f.attr].methods.get("__init__")
                ref = self._singleton_ref(mod, base.id)
                if ref is not None:
                    return self.method(ref, f.attr)
                if base.id in mod.classes:
                    return mod.classes[base.id].methods.get(f.attr)
            elif (isinstance(base, ast.Attribute) and isinstance(base.value, ast.Name)
                  and base.value.id in ("self", "cls") and cls is not None):
                ci = self.class_info("%s::%s" % (mod.dotted, cls))
                if ci is not None:
                    ref = ci.attr_types.get(base.attr)
                    if ref is not None:
                        return self.method(ref, f.attr)
            elif (isinstance(base, ast.Call) and isinstance(base.func, ast.Name)
                  and base.func.id == "super" and cls is not None):
                ci = self.class_info("%s::%s" % (mod.dotted, cls))
                m = self.modules.get(mod.dotted)
                if ci is not None and ci.bases and m is not None:
                    bref = self._class_ref(m, ci.bases[0])
                    if bref:
                        return self.method(bref, f.attr)
        return None


class _FuncWalker:
    """Extract acquire/call/wait events from one function body, tracking
    the lexically-held lock set through `with` statements."""

    def __init__(self, res: _Resolver, mod: _ModuleInfo, fi: _FuncInfo,
                 result: LockGraphResult):
        self.res = res
        self.mod = mod
        self.fi = fi
        self.result = result
        self.events: List[_Event] = []

    def run(self) -> List[_Event]:
        node = self.fi.node
        body = node.body if hasattr(node, "body") else []
        self._walk(body, ())
        return self.events

    def _emit(self, kind: str, target: str, held: Tuple[str, ...], line: int) -> None:
        self.events.append(_Event(kind, target, held, self.mod.file, line,
                                  self.fi.qualname))

    def _walk(self, stmts: Sequence[ast.AST], held: Tuple[str, ...]) -> None:
        for st in stmts:
            self._walk_stmt(st, held)

    def _walk_stmt(self, st: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(st, ast.With):
            new_held = held
            for item in st.items:
                ld = self.res.resolve_lock(self.mod, self.fi.cls, item.context_expr)
                for e in ast.walk(item.context_expr):
                    if isinstance(e, ast.Call):
                        self._scan_call(e, new_held)
                if ld is not None:
                    self.result.sites += 1
                    if ld.lock_id in new_held and not ld.reentrant:
                        if not _line_has(self.mod, st.lineno, "lock-order-ok"):
                            self.result.findings.append(Finding(
                                "lock-reentry", self.mod.file, st.lineno,
                                "non-reentrant lock %r re-acquired while already "
                                "held in %s" % (ld.lock_id, self.fi.qualname)))
                    else:
                        self._emit("acquire", ld.lock_id, new_held, st.lineno)
                        new_held = new_held + (ld.lock_id,)
            self._walk(st.body, new_held)
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: body runs later (thread target, callback) with
            # no lexical locks held
            nested = _FuncInfo("%s.<locals>.%s" % (self.fi.qualname, st.name),
                               self.fi.file, st, self.fi.cls, self.fi.module)
            w = _FuncWalker(self.res, self.mod, nested, self.result)
            w._walk(st.body, ())
            self.events.extend(w.events)
            return
        if isinstance(st, ast.Lambda):
            w = _FuncWalker(self.res, self.mod, self.fi, self.result)
            w._walk_expr_only(st.body, ())
            self.events.extend(w.events)
            return
        # generic statement: scan expressions for calls, recurse into
        # compound-statement bodies with the same held set
        for fname, value in ast.iter_fields(st):
            if isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.stmt):
                        self._walk_stmt(v, held)
                    elif isinstance(v, ast.excepthandler):
                        if v.type is not None:
                            self._walk_expr_only(v.type, held)
                        self._walk(v.body, held)
                    elif isinstance(v, ast.AST):
                        self._walk_expr_only(v, held)
            elif isinstance(value, ast.AST):
                self._walk_expr_only(value, held)

    def _walk_expr_only(self, expr: ast.AST, held: Tuple[str, ...]) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._scan_call(node, held)
            elif isinstance(node, (ast.Lambda,)):
                pass  # lambdas walked via ast.walk already; calls inside
                      # run later but a lexical held-set over-approximates
                      # safely only for direct bodies, so leave as-is
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                pass

    def _scan_call(self, node: ast.Call, held: Tuple[str, ...]) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "acquire":
                ld = self.res.resolve_lock(self.mod, self.fi.cls, f.value)
                if ld is not None:
                    self.result.sites += 1
                    self._emit("acquire", ld.lock_id, held, node.lineno)
                    return
            elif f.attr in ("wait", "wait_for"):
                ld = self.res.resolve_lock(self.mod, self.fi.cls, f.value)
                if ld is not None:
                    self.result.sites += 1
                    others = tuple(h for h in held if h != ld.lock_id)
                    if others and not _line_has(self.mod, node.lineno, "wait-holding-ok"):
                        self.result.findings.append(Finding(
                            "wait-while-holding", self.mod.file, node.lineno,
                            "%s waits on %r while holding %s — the held lock "
                            "is pinned for the whole wait" % (
                                self.fi.qualname, ld.lock_id, list(others))))
                    self._emit("wait", ld.lock_id, held, node.lineno)
                    return
        fi = self.res.resolve_call(self.mod, self.fi.cls, node)
        if fi is not None:
            self._emit("call", fi.qualname, held, node.lineno)


# -- graph assembly -------------------------------------------------------

def _propagate(events_by_func: Dict[str, List[_Event]]):
    """Fixpoint: for each function, the set of locks it may acquire
    (directly or transitively), with a trace for witness paths.

    trace[f][lock] = ("site", file, line) | ("via", file, line, callee)
    """
    may: Dict[str, Dict[str, Tuple]] = {f: {} for f in events_by_func}
    callers: Dict[str, Set[str]] = {}
    for f, evs in events_by_func.items():
        for e in evs:
            if e.kind == "call":
                callers.setdefault(e.target, set()).add(f)
    work = list(events_by_func.keys())
    while work:
        f = work.pop()
        cur = may.setdefault(f, {})
        changed = False
        for e in events_by_func.get(f, ()):
            if e.kind == "acquire":
                if e.target not in cur:
                    cur[e.target] = ("site", e.file, e.line)
                    changed = True
            elif e.kind == "call":
                for lock in may.get(e.target, {}):
                    if lock not in cur:
                        cur[lock] = ("via", e.file, e.line, e.target)
                        changed = True
        if changed:
            for c in callers.get(f, ()):
                if c not in work:
                    work.append(c)
    return may


def _witness_chain(may, func_or_lock_trace, events_by_func, lock: str,
                   depth: int = 0) -> List[str]:
    tr = func_or_lock_trace
    if tr is None or depth > 8:
        return []
    if tr[0] == "site":
        return ["%s:%d" % (tr[1], tr[2])]
    _via, file, line, callee = tr
    sub = may.get(callee, {}).get(lock)
    return ["%s:%d" % (file, line)] + _witness_chain(may, sub, events_by_func,
                                                    lock, depth + 1)


def _find_cycles(edges: Dict[Tuple[str, str], List[str]]) -> List[List[str]]:
    """Tarjan SCC; any SCC with >1 node (or a self-loop) is a cycle."""
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    def strongconnect(v: str) -> None:
        # iterative Tarjan to dodge recursion limits on big graphs
        call_stack = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        while call_stack:
            node, it = call_stack[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    call_stack.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in onstack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            call_stack.pop()
            if call_stack:
                parent = call_stack[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1 or (node in graph.get(node, ())):
                    sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def scan_sources(sources: Dict[str, Tuple[str, str]]) -> LockGraphResult:
    """Run the lock-order pass.

    `sources` maps dotted module name -> (display file path, source text).
    """
    result = LockGraphResult()
    modules: Dict[str, _ModuleInfo] = {}
    for dotted, (file, text) in sorted(sources.items()):
        mi = _collect_module(dotted, file, text)
        if mi is not None:
            modules[dotted] = mi
    result.modules = modules

    res = _Resolver(modules)
    result.resolver = res
    for mi in modules.values():
        for name, ld in mi.locks.items():
            result.locks.setdefault(ld.lock_id, ld)
        for ci in mi.classes.values():
            for ld in ci.lock_attrs.values():
                result.locks.setdefault(ld.lock_id, ld)

    events_by_func: Dict[str, List[_Event]] = {}
    for mi in modules.values():
        funcs = list(mi.functions.values())
        for ci in mi.classes.values():
            funcs.extend(ci.methods.values())
        for fi in funcs:
            w = _FuncWalker(res, mi, fi, result)
            events_by_func[fi.qualname] = w.run()

    may = _propagate(events_by_func)

    reentrant_ids = {lid for lid, ld in result.locks.items() if ld.reentrant}
    for f, evs in events_by_func.items():
        for e in evs:
            if e.kind == "acquire":
                for h in e.held:
                    if h == e.target:
                        continue
                    result.edges.setdefault((h, e.target), []).append(
                        "%s:%d" % (e.file, e.line))
            elif e.kind == "call":
                for lock, tr in may.get(e.target, {}).items():
                    for h in e.held:
                        if h == lock:
                            # same lock id via a call edge: per-instance
                            # locks share ids, so this is only a hazard
                            # for true singletons; too noisy to report
                            continue
                        chain = ["%s:%d" % (e.file, e.line)] + _witness_chain(
                            may, may.get(e.target, {}).get(lock), events_by_func, lock)
                        result.edges.setdefault((h, lock), []).append(
                            " -> ".join(chain))

    result.events = events_by_func
    result.cycles = _find_cycles(result.edges)
    for comp in result.cycles:
        comp_set = set(comp)
        lines = ["potential deadlock: lock-order cycle over %s" % (comp,)]
        first_file, first_line = "", 0
        for (a, b), wits in sorted(result.edges.items()):
            if a in comp_set and b in comp_set:
                lines.append("  %s -> %s   witness: %s" % (a, b, wits[0]))
                if not first_file:
                    head = wits[0].split(" -> ")[0]
                    first_file, _, ln = head.rpartition(":")
                    first_line = int(ln) if ln.isdigit() else 0
        result.findings.append(Finding(
            "lock-cycle", first_file or "<package>", first_line,
            "\n".join(lines)))
    return result
