"""Chunk-granular mesh checkpoints: host-side snapshots of the step loop.

The chunked mesh plane (parallel/mesh_chunk.py) already owns a natural
recovery boundary — the host regains control between chunk steps, and
carry shapes are ladder-stable across the whole run — so a checkpoint
is cheap and exact: `jax.device_get` the carries right after a step
returns (the flag readback has already synced the device, and donation
only claims an array when it is passed into the NEXT step call), plus
the chunk index. Feed offsets are implied: chunk k reads the device
slice [k*chunk_cap, (k+1)*chunk_cap) of the immutable padded feeds, so
resuming at `next_chunk` replays exactly the unexecuted slices.

Entries are generation-guarded exactly like the subtree spool
(adaptive/spool.py) and the resident pins: the key carries the feed
tables' write-generation vector at snapshot time, and `get` revalidates
it — DML on any table the run read makes the checkpoint unreachable
(counted as recovery.invalidations) instead of serving stale carries.

The store is host-memory LRU, process-wide, and deliberately small:
checkpoints exist to survive a fault *within or immediately after* a
run, not to archive history. A successful run discards its own entry.
"""

from __future__ import annotations

import dataclasses
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from collections import OrderedDict
from typing import Dict, Optional, Tuple

# counter names exported through /v1/metrics (registered as zero-valued
# keys by register_recovery_metrics so the surface is visible before the
# first fault)
CHECKPOINTS_TAKEN = "recovery.checkpoints"
RESUMES = "recovery.resumes"
INVALIDATIONS = "recovery.invalidations"
SPOOLED_STAGE_HITS = "recovery.spooled_stage_hits"

_COUNTERS = (CHECKPOINTS_TAKEN, RESUMES, INVALIDATIONS, SPOOLED_STAGE_HITS)


def register_recovery_metrics() -> None:
    """Make the recovery counters appear in /v1/metrics snapshots at
    zero (a counter otherwise only materializes on first bump, hiding
    the surface from dashboards until something fails)."""
    from trino_tpu.runtime.metrics import METRICS

    for name in _COUNTERS:
        METRICS.increment(name, 0.0)


@dataclasses.dataclass
class MeshCheckpoint:
    """One resumable position in a chunk-step loop.

    `carries_host` is the host (numpy-leaf) pytree of device carries as
    of having completed chunks [0, next_chunk); `resolved_caps` is the
    capacity dict the carries were shaped under, so a resume that lands
    after an overflow cap-bump can re-pad them onto the new rungs.
    """

    next_chunk: int  # first chunk NOT yet executed
    n_chunks: int
    chunk_cap: int
    resolved_caps: Dict[str, int]
    carries_host: tuple
    tables: Tuple[Tuple[str, str, str], ...]
    generations: Tuple[int, ...]
    query_id: str = ""

    # -- host portability (replicated meshes / multi-host failover) --
    # `carries_host` is already a pure host value (numpy-leaf pytrees of
    # the engine's container dataclasses), so a checkpoint serializes
    # without touching the device: a sibling sub-mesh — or another host
    # in the pod — deserializes the bytes and `_restore_carries` places
    # them under ITS sharding. The generation vector travels inside, so
    # the receiving store's `get` revalidation still fences DML that
    # landed between snapshot and restore.
    def to_bytes(self) -> bytes:
        import pickle

        return pickle.dumps(self, protocol=pickle.HIGHEST_PROTOCOL)

    @staticmethod
    def from_bytes(data: bytes) -> "MeshCheckpoint":
        import pickle

        ckpt = pickle.loads(data)
        if not isinstance(ckpt, MeshCheckpoint):
            raise TypeError(
                f"checkpoint bytes decoded to {type(ckpt).__name__}"
            )
        return ckpt


class MeshCheckpointStore:
    """Generation-guarded LRU of mesh checkpoints, keyed by the program
    identity (the mesh record key minus capacities, so a resume across
    overflow cap bumps still finds its checkpoint)."""

    def __init__(self, max_entries: int = 16):
        self._lock = named_lock("MeshCheckpointStore._lock")
        self._entries: "OrderedDict[tuple, MeshCheckpoint]" = OrderedDict()  # guarded_by: _lock
        self._max = max_entries
        self.taken = 0
        self.resumed = 0
        self.invalidated = 0
        # park lifecycle (runtime/scheduler.py): keys whose entry is a
        # *parked* query's snapshot — the query's device memory is
        # gone, so the entry is the only copy of its progress. Parked
        # keys are pinned (immune to LRU eviction) and their host
        # bytes are accounted against the session park budget.
        self._parked: Dict[tuple, int] = {}  # guarded_by: _lock — key -> accounted bytes
        # resource group a parked entry is accounted to (admission-
        # weighted park budgets: runtime/scheduler.py park_budget_for)
        self._park_groups: Dict[tuple, str] = {}  # guarded_by: _lock
        self.parked_refused = 0

    def _generations(self, tables) -> Tuple[int, ...]:
        from trino_tpu.resident import GENERATIONS

        return GENERATIONS.snapshot(tables)

    def put(self, key: tuple, ckpt: MeshCheckpoint) -> None:
        from trino_tpu.runtime.metrics import METRICS

        with self._lock:
            self._entries[key] = ckpt
            self._entries.move_to_end(key)
            self.taken += 1
            while len(self._entries) > self._max:
                # evict oldest UNPARKED entry: a parked entry is the
                # only copy of its query's progress
                victim = next(
                    (k for k in self._entries if k not in self._parked),
                    None,
                )
                if victim is None:
                    break
                del self._entries[victim]
        METRICS.increment(CHECKPOINTS_TAKEN)

    def get(self, key: tuple) -> Optional[MeshCheckpoint]:
        """Return a live checkpoint, or None. A stale generation vector
        (DML landed on a feed table since the snapshot) drops the entry:
        its carries aggregate rows the tables no longer hold."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            if self._generations(e.tables) != e.generations:
                del self._entries[key]
                self.invalidated += 1
                from trino_tpu.runtime.metrics import METRICS

                METRICS.increment(INVALIDATIONS)
                return None
            self._entries.move_to_end(key)
            return e

    def note_resume(self) -> None:
        from trino_tpu.runtime.metrics import METRICS

        with self._lock:
            self.resumed += 1
        METRICS.increment(RESUMES)

    def discard(self, key: tuple) -> None:
        with self._lock:
            self._entries.pop(key, None)
            self._parked.pop(key, None)
            self._park_groups.pop(key, None)

    # -- park lifecycle (preemptive scheduler) ------------------------
    @staticmethod
    def _ckpt_nbytes(ckpt: MeshCheckpoint) -> int:
        """Host footprint of a snapshot: sum of numpy-leaf nbytes."""
        import jax
        import numpy as np

        total = 0
        for leaf in jax.tree_util.tree_leaves(ckpt.carries_host):
            arr = np.asarray(leaf)
            total += int(arr.nbytes)
        return total

    def park(self, key: tuple, ckpt: MeshCheckpoint,
             max_bytes: int, group: Optional[str] = None) -> bool:
        """Install a parked query's snapshot, accounting its host bytes
        against `max_bytes`. With `group=None` the budget is shared by
        every parked entry (the park_max_bytes pool); with a group the
        budget is that GROUP's share of the admission-weighted pool
        (mesh_park_max_bytes apportioned by scheduler weight) and only
        same-group entries count against it — one group past its share
        cannot starve another's parks. Returns False (store untouched)
        when the budget refuses — the caller keeps its device carries
        and runs to completion."""
        from trino_tpu.runtime.metrics import METRICS

        nbytes = self._ckpt_nbytes(ckpt)
        with self._lock:
            in_use = sum(
                b for k, b in self._parked.items()
                if k != key
                and (group is None or self._park_groups.get(k) == group)
            )
            if max_bytes >= 0 and in_use + nbytes > max_bytes:
                self.parked_refused += 1
                return False
            self._entries[key] = ckpt
            self._entries.move_to_end(key)
            self._parked[key] = nbytes
            if group is not None:
                self._park_groups[key] = group
            else:
                self._park_groups.pop(key, None)
            self.taken += 1
        METRICS.increment(CHECKPOINTS_TAKEN)
        return True

    def unpark(self, key: tuple, keep: bool = True) -> None:
        """Release a parked entry's budget accounting. `keep=True`
        leaves the snapshot in the store as an ordinary LRU entry (the
        resume path — and drain failover, which re-reads it on a
        sibling — still finds it); `keep=False` drops it entirely
        (typed kills: a dead query must never resume)."""
        with self._lock:
            self._parked.pop(key, None)
            self._park_groups.pop(key, None)
            if not keep:
                self._entries.pop(key, None)

    def parked_bytes(self) -> int:
        with self._lock:
            return sum(self._parked.values())

    def parked_count(self) -> int:
        with self._lock:
            return len(self._parked)

    # -- host-boundary transfer (replicated meshes) -------------------
    def export_bytes(self, key: tuple) -> Optional[bytes]:
        """Serialize a live checkpoint for transfer across the host
        boundary. Goes through `get` so a stale generation vector is
        never exported — the receiver would only re-discover the
        invalidation it could have learned here."""
        ckpt = self.get(key)
        return None if ckpt is None else ckpt.to_bytes()

    def import_bytes(self, key: tuple, data: bytes,
                     rebase_epoch: bool = False) -> bool:
        """Install a checkpoint received from another host (or another
        store). The entry lands under THIS process's generation check:
        if local DML advanced any feed table past the snapshot's
        vector, the very next `get` drops it — imported bytes can never
        resurface pre-write state. Returns False on undecodable bytes
        (a truncated transfer must not poison the store).

        `rebase_epoch=True` is the cross-HOST transport mode (the
        fabric's receive/pull paths): the global generation epoch
        counts process-local wholesale events (catalog registration,
        COMMIT), so two coordinators' epochs are incomparable and a
        peer's snapshot would be stillborn under the local epoch.
        Rebasing adopts the local epoch per table while KEEPING the
        snapshot's per-table write counters — table-level DML fencing
        stays live across the wire."""
        try:
            ckpt = MeshCheckpoint.from_bytes(data)
        except Exception:
            return False
        if rebase_epoch and ckpt.tables:
            from trino_tpu.resident import GENERATIONS

            ckpt = dataclasses.replace(ckpt, generations=tuple(sorted(
                (k, (GENERATIONS.get(k)[0], gen))
                for (k, (_ep, gen)) in ckpt.generations
            )))
        self.put(key, ckpt)
        return True

    def invalidate_table(self, catalog: str, schema: str, table: str) -> int:
        """Proactive drop for the DML path (engine.py): generation
        guarding already makes stale entries unreachable lazily; this
        reclaims their host memory eagerly and makes the invalidation
        visible in metrics at write time."""
        triple = (catalog.lower(), schema.lower(), table.lower())
        with self._lock:
            stale = [
                k for k, e in self._entries.items() if triple in e.tables
            ]
            for k in stale:
                del self._entries[k]
            self.invalidated += len(stale)
        if stale:
            from trino_tpu.runtime.metrics import METRICS

            METRICS.increment(INVALIDATIONS, float(len(stale)))
        return len(stale)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._parked.clear()
            self._park_groups.clear()

    def reset_stats(self) -> None:
        """Zero the lifetime counters (corpus generation and tests pin
        exact counts; mirrors RESIDENT.reset_stats)."""
        with self._lock:
            self.taken = 0
            self.resumed = 0
            self.invalidated = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats_line(self) -> str:
        with self._lock:
            return (
                f"checkpoints: entries={len(self._entries)} "
                f"taken={self.taken} resumed={self.resumed} "
                f"invalidated={self.invalidated}"
            )


# the process singleton (one coordinator process, one store — mirrors
# adaptive.spool.SPOOL and resident.RESIDENT)
CHECKPOINTS = MeshCheckpointStore()
