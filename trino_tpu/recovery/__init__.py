"""Recovery tier: chunk-granular checkpoint/resume for the mesh plane
and spooled stage-output reuse for distributed retry.

Two complementary halves of one idea — degrade by the increment that
failed, not by the whole plan:

- `checkpoint`: the chunked mesh step loop snapshots its device carries
  at configurable chunk boundaries into a host-side, generation-guarded
  store; MeshStuck / device loss / chaos faults resume from the last
  checkpoint instead of chunk 0 (parallel/mesh_chunk.py drives it).
- `stage_spool`: completed fragment outputs are teed (pipelined) or
  lifted from durable FTE spool files into the adaptive tier's subtree
  spool, so QUERY-level retry substitutes finished stages as
  SpooledValuesNode fragments rather than recomputing them.

Both stores follow the resident tier's invalidation protocol: entries
carry per-table write-generation vectors and a mismatch makes them
unreachable, so DML can never resurface pre-write state.
"""

from trino_tpu.recovery.checkpoint import (
    CHECKPOINTS,
    CHECKPOINTS_TAKEN,
    INVALIDATIONS,
    RESUMES,
    SPOOLED_STAGE_HITS,
    MeshCheckpoint,
    MeshCheckpointStore,
    register_recovery_metrics,
)
from trino_tpu.recovery.stage_spool import (
    RECORDER,
    StageOutputRecorder,
    fragment_recordable,
    fragment_spool_key,
    harvest_recorded_stages,
    record_committed_stage,
    substitute_spooled_fragments,
)

__all__ = [
    "CHECKPOINTS",
    "CHECKPOINTS_TAKEN",
    "INVALIDATIONS",
    "RESUMES",
    "SPOOLED_STAGE_HITS",
    "MeshCheckpoint",
    "MeshCheckpointStore",
    "register_recovery_metrics",
    "RECORDER",
    "StageOutputRecorder",
    "fragment_recordable",
    "fragment_spool_key",
    "harvest_recorded_stages",
    "record_committed_stage",
    "substitute_spooled_fragments",
]
