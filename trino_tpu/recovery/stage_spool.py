"""Spooled stage outputs: completed fragments survive a query failure.

The pipelined page plane streams fragment outputs through pull+ack
OutputBuffers, which DROP pages below the acknowledged token — by the
time a downstream failure fires, the upstream stage's output is gone
and QUERY-level retry (PR 3) recomputes everything. This module tees
the output at production time instead: when the session opts in
(`recovery_spool_stages`), every non-root task's terminal
PartitionedOutputOperator writes through a `RecordingBuffer` proxy
(the _MidFailureBuffer pattern) that keeps a host-side copy of each
wire page. When the query fails and retries, the coordinator harvests
every FULLY completed fragment into the generation-guarded subtree
spool (adaptive/spool.py) and substitutes each with a
`SpooledValuesNode` fragment — partitioning flipped to "single" so one
task replays the recorded rows and its PartitionedOutputOperator
re-partitions them for the consumers — so only the work that actually
failed is recomputed.

The FTE scheduler gets the same treatment from its durable side:
committed task attempts already persist per-partition spool files, so
`record_committed_stage` lifts a settled stage's files into the same
subtree spool, and a later attempt of the same query (QUERY retry over
FTE, or a fresh submission) substitutes it without touching the
upstream tables.

Eligibility mirrors the adaptive spool's guard rails: round-trippable
field types only, bounded by MAX_SPOOL_ROWS, no merge-ordered outputs
(a single replay task cannot reproduce per-producer sorted streams),
and generation guarding makes entries from before a DML unreachable.
"""

from __future__ import annotations

import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import Dict, List, Optional, Tuple

from trino_tpu.adaptive.spool import (
    MAX_SPOOL_ROWS,
    SPOOL,
    _field_materializable,
    plan_fingerprint,
    spooled_node,
    subtree_tables,
)


def _pages_to_rows(pages) -> List[list]:
    """Decode wire pages to python rows host-side (the coordinator's
    _page_rows rules, local copy to keep recovery import-light)."""
    import numpy as np

    from trino_tpu.block import decode_values
    from trino_tpu.exec.serde import HostNested

    rows: List[list] = []
    for page in pages:
        cols = []
        for t, data, valid, dvals in zip(
            page.types, page.columns, page.valids, page.dictionaries
        ):
            if isinstance(data, HostNested):
                cols.append(data.to_pylist())
                continue
            ok = (
                valid
                if valid is not None
                else np.ones(len(data), dtype=bool)
            )
            cols.append(decode_values(t, data, ok, dvals))
        rows.extend(list(r) for r in zip(*cols))
    return rows


def fragment_spool_key(fragment) -> str:
    """Spool key for one fragment's complete output. Fingerprints the
    FRAGMENT root (RemoteSourceNodes and partial-agg shapes included),
    not the logical plan: two fragments are interchangeable exactly
    when their physical trees match."""
    return "frag:" + plan_fingerprint(fragment.root)


def subplan_tables(sp) -> Tuple[Tuple[str, str, str], ...]:
    """Generation-guard domain of a fragment's output: every table read
    by the fragment OR any producer below it (a stale upstream table
    makes the recorded output stale even though this fragment's own
    scans are elsewhere)."""
    out = set()
    for s in _walk(sp):
        out.update(subtree_tables(s.fragment.root))
    return tuple(sorted(out))


def _walk(sp):
    yield sp
    for c in sp.children:
        yield from _walk(c)


def fragment_recordable(sp, is_root: bool) -> bool:
    """Whether a fragment's output may be recorded for replay. The root
    fragment is excluded (its consumer is the client: if it finished,
    the query succeeded); merge-ordered outputs are excluded (one
    replay task cannot reproduce N per-producer sorted streams); every
    output field must round-trip through python rows."""
    f = sp.fragment
    if is_root or f.output_merge_keys:
        return False
    return all(_field_materializable(fl.type) for fl in f.root.fields)


class RecordingBuffer:
    """Sink-buffer proxy that tees each produced wire page into the
    recorder while passing everything through (the _MidFailureBuffer
    shape). Completion is only signalled on a clean set_no_more_pages —
    a task that dies mid-stream leaves its recording incomplete and the
    fragment stays ineligible."""

    def __init__(self, inner, recorder, key, task_key):
        self._inner = inner
        self._recorder = recorder
        self._key = key
        self._task_key = task_key

    def enqueue(self, partition, page):
        self._inner.enqueue(partition, page)
        self._recorder.add_page(self._key, self._task_key, page)

    def set_no_more_pages(self):
        self._inner.set_no_more_pages()
        self._recorder.task_done(self._key, self._task_key)


class _FragmentRecording:
    __slots__ = ("expected_tasks", "pages", "done_tasks", "rows",
                 "overflowed")

    def __init__(self, expected_tasks: int):
        self.expected_tasks = expected_tasks
        self.pages: List[object] = []
        self.done_tasks: set = set()
        self.rows = 0
        self.overflowed = False

    def complete(self) -> bool:
        return (
            not self.overflowed
            and len(self.done_tasks) >= self.expected_tasks
        )


class StageOutputRecorder:
    """Process-wide registry of in-flight fragment-output recordings,
    keyed (query_id, fragment_id) per attempt namespace. The scheduler
    declares expected task counts up front; RecordingBuffers feed pages
    in; the coordinator harvests complete fragments into the subtree
    spool on retry and purges the query's recordings at finalize."""

    def __init__(self):
        self._lock = named_lock("StageOutputRecorder._lock")
        self._recs: Dict[Tuple[str, int], _FragmentRecording] = {}

    def expect(self, query_id: str, fragment_id: int, n_tasks: int) -> None:
        with self._lock:
            self._recs[(query_id, fragment_id)] = _FragmentRecording(n_tasks)

    def add_page(self, key, task_key, page) -> None:
        with self._lock:
            rec = self._recs.get(key)
            if rec is None or rec.overflowed:
                return
            rec.rows += int(page.row_count)
            if rec.rows > MAX_SPOOL_ROWS:
                # unbounded stage: recording it would trade a retry for
                # an equally unbounded host copy — drop, keep the flag
                rec.overflowed = True
                rec.pages = []
                return
            rec.pages.append(page)

    def task_done(self, key, task_key) -> None:
        with self._lock:
            rec = self._recs.get(key)
            if rec is not None:
                rec.done_tasks.add(task_key)

    def recording_buffer(self, inner, query_id: str, fragment_id: int,
                         task_key: str):
        return RecordingBuffer(
            inner, self, (query_id, fragment_id), task_key
        )

    def complete_pages(self, query_id: str, fragment_id: int):
        with self._lock:
            rec = self._recs.get((query_id, fragment_id))
            if rec is None or not rec.complete():
                return None
            return list(rec.pages)

    def purge(self, query_id_prefix: str) -> None:
        """Drop every recording whose query id is the prefix or one of
        its `r<N>` retry namespaces (qN / qNr1 / ...)."""
        with self._lock:
            for qid, fid in [
                k for k in self._recs
                if k[0] == query_id_prefix
                or k[0].startswith(query_id_prefix + "r")
            ]:
                del self._recs[(qid, fid)]

    def clear(self) -> None:
        with self._lock:
            self._recs.clear()


RECORDER = StageOutputRecorder()


def _spool_rows(sp, rows) -> None:
    from trino_tpu.sql.stats import PlanStats

    SPOOL.put(
        fragment_spool_key(sp.fragment),
        rows,
        sp.fragment.root.fields,
        PlanStats(float(max(len(rows), 1))),
        subplan_tables(sp),
    )


def harvest_recorded_stages(query_id: str, subplan) -> int:
    """Lift every fully-recorded fragment of a failed attempt into the
    subtree spool (called by QUERY retry before replanning the next
    attempt). Returns the number of fragments banked."""
    banked = 0
    stages = list(_walk(subplan))
    root_id = subplan.fragment.id
    for sp in stages:
        if not fragment_recordable(sp, sp.fragment.id == root_id):
            continue
        pages = RECORDER.complete_pages(query_id, sp.fragment.id)
        if pages is None:
            continue
        try:
            rows = _pages_to_rows(pages)
        except Exception:
            continue  # an undecodable page must not fail the retry
        if len(rows) > MAX_SPOOL_ROWS:
            continue
        _spool_rows(sp, rows)
        banked += 1
    return banked


def record_committed_stage(spool_dir: str, task_keys, sp,
                           n_out: int, is_root: bool) -> bool:
    """FTE settle hook: a stage whose every partition committed has
    durable per-partition spool files — decode them once into the
    subtree spool so the NEXT attempt of this query substitutes the
    stage instead of re-running it. `task_keys` lists the committed
    attempt task keys (spool directory names), one per task; each task
    dir holds pages for every OUTPUT partition 0..n_out-1."""
    import os

    from trino_tpu.runtime.spool import read_spool

    if not fragment_recordable(sp, is_root):
        return False
    rows: List[list] = []
    try:
        for task_key in task_keys:
            task_dir = os.path.join(spool_dir, task_key)
            for p in range(n_out):
                token, done = 0, False
                while not done:
                    pages, token, done = read_spool(task_dir, p, token)
                    rows.extend(_pages_to_rows(pages))
                    if len(rows) > MAX_SPOOL_ROWS:
                        return False
    except Exception:
        return False  # a spool-read hiccup must not fail the settle
    _spool_rows(sp, rows)
    return True


def substitute_spooled_fragments(subplan, span=None):
    """Rebuild a SubPlan tree with every fragment whose complete output
    sits live in the subtree spool replaced by a single-task
    SpooledValuesNode fragment (children dropped — the replay has no
    remote inputs). Outermost-first: a spooled fragment subsumes its
    producers. Returns (new_subplan, substituted_fragment_ids)."""
    import dataclasses

    from trino_tpu.runtime.metrics import METRICS
    from trino_tpu.sql.fragmenter import SubPlan

    from trino_tpu.recovery.checkpoint import SPOOLED_STAGE_HITS

    hits: List[int] = []
    root_id = subplan.fragment.id

    def sub(sp):
        f = sp.fragment
        if fragment_recordable(sp, f.id == root_id):
            key = fragment_spool_key(f)
            entry = SPOOL.get(key, subplan_tables(sp))
            if entry is not None:
                hits.append(f.id)
                METRICS.increment(SPOOLED_STAGE_HITS)
                if span is not None:
                    span.event(
                        "spooled_stage_hit", fragment=f.id,
                        rows=len(entry.rows),
                    )
                node = spooled_node(
                    entry, key, f"recovered stage {f.id}"
                )
                return SubPlan(
                    dataclasses.replace(
                        f, root=node, partitioning="single",
                        suggested_partitions=None,
                    ),
                    [],
                )
        return SubPlan(f, [sub(c) for c in sp.children])

    return sub(subplan), hits
