// Native exchange hot path: single-pass partition scatter.
//
// Role analogue: the reference's "native" layer is JIT-generated JVM
// bytecode for the data plane's inner loops (SURVEY.md §2.9) — here the
// HOST-side inner loops around the XLA device path are C++. This module
// replaces PartitionedOutputOperator's per-partition boolean-mask passes
// (O(P·N) in numpy) with one O(N) scatter pass over all partitions
// (output/PartitionedOutputOperator.java:191 PagePartitioner — the
// per-partition PositionsAppenders collapsed into one cache-friendly
// sweep).
//
// Build: g++ -O3 -shared -fPIC -o libpagesplit.so pagesplit.cpp
// Loaded via ctypes (trino_tpu/native/__init__.py) with a pure-numpy
// fallback when the toolchain is unavailable.

#include <cstdint>
#include <cstring>

extern "C" {

// Count rows per partition. pids[i] in [0, n_parts) or -1 for dead rows.
void partition_counts(const int32_t* pids, int64_t n_rows, int32_t n_parts,
                      int64_t* counts /* out, size n_parts */) {
    for (int32_t p = 0; p < n_parts; ++p) counts[p] = 0;
    for (int64_t i = 0; i < n_rows; ++i) {
        int32_t p = pids[i];
        if (p >= 0 && p < n_parts) counts[p]++;
    }
}

// Scatter one fixed-width column into per-partition output buffers in a
// single pass. outs[p] must hold counts[p]*item_size bytes. `offsets` is
// scratch of size n_parts (zeroed here).
void scatter_column(const uint8_t* data, int64_t item_size,
                    const int32_t* pids, int64_t n_rows, int32_t n_parts,
                    uint8_t** outs, int64_t* offsets /* scratch */) {
    for (int32_t p = 0; p < n_parts; ++p) offsets[p] = 0;
    switch (item_size) {
        case 1:
            for (int64_t i = 0; i < n_rows; ++i) {
                int32_t p = pids[i];
                if (p < 0 || p >= n_parts) continue;
                outs[p][offsets[p]++] = data[i];
            }
            return;
        case 4:
            for (int64_t i = 0; i < n_rows; ++i) {
                int32_t p = pids[i];
                if (p < 0 || p >= n_parts) continue;
                reinterpret_cast<uint32_t*>(outs[p])[offsets[p]++] =
                    reinterpret_cast<const uint32_t*>(data)[i];
            }
            return;
        case 8:
            for (int64_t i = 0; i < n_rows; ++i) {
                int32_t p = pids[i];
                if (p < 0 || p >= n_parts) continue;
                reinterpret_cast<uint64_t*>(outs[p])[offsets[p]++] =
                    reinterpret_cast<const uint64_t*>(data)[i];
            }
            return;
        default:
            for (int64_t i = 0; i < n_rows; ++i) {
                int32_t p = pids[i];
                if (p < 0 || p >= n_parts) continue;
                std::memcpy(outs[p] + offsets[p] * item_size,
                            data + i * item_size, item_size);
                offsets[p]++;
            }
    }
}

// Gather rows selected by a boolean mask into a compact output buffer
// (the Page.compact / live-row extraction inner loop).
int64_t mask_gather(const uint8_t* data, int64_t item_size,
                    const uint8_t* mask, int64_t n_rows, uint8_t* out) {
    int64_t w = 0;
    for (int64_t i = 0; i < n_rows; ++i) {
        if (!mask[i]) continue;
        std::memcpy(out + w * item_size, data + i * item_size, item_size);
        ++w;
    }
    return w;
}

}  // extern "C"
