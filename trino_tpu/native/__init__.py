"""Native runtime components (C++ via ctypes).

The compute path is XLA; the HOST runtime's inner loops (exchange page
splitting, mask compaction) are C++ — the role the reference fills with
JIT bytecode + Slice buffers (SURVEY.md §2.9). The library is compiled
on first use with the system toolchain and cached next to the source;
every entry point has a numpy fallback, so the engine runs (slower)
without a compiler."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import List, Optional

import numpy as np

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "pagesplit.cpp")
_LIB = os.path.join(_DIR, "libpagesplit.so")

_lock = named_lock("native._lock")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> Optional[str]:
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return _LIB
    try:
        # compile to a per-pid temp path, then atomically publish: the
        # in-process lock doesn't cover concurrent PROCESSES racing the
        # first build, and dlopen of a half-written .so is undefined
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _LIB)
        return _LIB
    except Exception:
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        path = _build()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        lib.partition_counts.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
        ]
        lib.scatter_column.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.mask_gather.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p,
        ]
        lib.mask_gather.restype = ctypes.c_int64
        _lib = lib
        return _lib


def _ptr(a: np.ndarray) -> ctypes.c_void_p:
    return ctypes.c_void_p(a.ctypes.data)


def partition_scatter(
    columns: List[np.ndarray], pids: np.ndarray, n_parts: int
) -> List[List[np.ndarray]]:
    """Split columns by per-row partition id in ONE pass per column.
    Returns [partition][column] arrays. pids: int32, -1 = drop."""
    lib = get_lib()
    pids = np.ascontiguousarray(pids, dtype=np.int32)
    n = len(pids)
    if lib is None:
        out = []
        for p in range(n_parts):
            m = pids == p
            out.append([np.ascontiguousarray(c[m]) for c in columns])
        return out
    counts = np.zeros(n_parts, dtype=np.int64)
    lib.partition_counts(_ptr(pids), n, n_parts, _ptr(counts))
    scratch = np.zeros(n_parts, dtype=np.int64)
    outs: List[List[np.ndarray]] = [[] for _ in range(n_parts)]
    for col in columns:
        col = np.ascontiguousarray(col)
        item = col.dtype.itemsize
        bufs = [np.empty(int(counts[p]), dtype=col.dtype) for p in range(n_parts)]
        ptrs = (ctypes.c_void_p * n_parts)(
            *[b.ctypes.data for b in bufs]
        )
        lib.scatter_column(
            _ptr(col), item, _ptr(pids), n, n_parts,
            ctypes.cast(ptrs, ctypes.c_void_p), _ptr(scratch),
        )
        for p in range(n_parts):
            outs[p].append(bufs[p])
    return outs


def mask_compact(columns: List[np.ndarray], mask: np.ndarray) -> List[np.ndarray]:
    """Extract live rows from each column (Page.from_batch inner loop)."""
    lib = get_lib()
    mask = np.ascontiguousarray(mask, dtype=np.uint8)
    if lib is None:
        m = mask.astype(bool)
        return [np.ascontiguousarray(c[m]) for c in columns]
    n_live = int(mask.sum())
    out = []
    for col in columns:
        col = np.ascontiguousarray(col)
        if col.ndim == 2:
            # long-decimal (n, k) limb rows: one gather of k-wide items
            item = col.dtype.itemsize * col.shape[1]
            buf = np.empty((n_live, col.shape[1]), dtype=col.dtype)
            w = lib.mask_gather(
                _ptr(col), item, _ptr(mask), len(mask), _ptr(buf)
            )
        else:
            buf = np.empty(n_live, dtype=col.dtype)
            w = lib.mask_gather(
                _ptr(col), col.dtype.itemsize, _ptr(mask), len(mask), _ptr(buf)
            )
        assert w == n_live
        out.append(buf)
    return out
