"""Columnar data model: device-resident structure-of-arrays batches.

Analogue of trino-spi's Page/Block layer (spi/Page.java:31 — a Page is
positionCount x Block[]; spi/block/Block.java:25; DictionaryBlock /
RunLengthEncodedBlock / VariableWidthBlock — SURVEY.md §2.5), re-designed
for XLA's static-shape model:

- A ``Column`` is one fixed-capacity device array plus an optional
  validity mask (NULLs) and an optional host-side string dictionary
  (VARCHAR values live on device as int32 codes — the DictionaryBlock
  idea made mandatory, which is the standard TPU answer to varlen data).
- A ``RelBatch`` is N columns sharing a capacity plus a ``live`` row mask.
  Where Trino pages have a dynamic positionCount, we keep static
  capacity (bucketed powers of two) and mask dead rows — filters only
  flip mask bits, and compaction is an explicit (cheap, vectorized)
  operation. This keeps every operator a fixed-shape XLA program.

Both are registered as pytrees so jitted kernels take them directly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T

MIN_CAPACITY = 16


_ONES_CACHE: dict = {}


def ones_mask(n: int) -> jnp.ndarray:
    """Cached all-true mask of length n. valid_mask/live_mask are called
    on the host side of every operator; a fresh jnp.ones per call is one
    device dispatch each — ruinous over a tunneled device link. Inside a
    jit trace the created value is a Tracer and MUST NOT be cached (it
    would leak out of its trace); there it folds into the program as a
    constant anyway."""
    a = _ONES_CACHE.get(n)
    if a is not None:
        return a
    a = jnp.ones(n, dtype=jnp.bool_)
    if isinstance(n, int) and not isinstance(a, jax.core.Tracer):
        _ONES_CACHE[n] = a  # unlocked-ok: GIL-atomic setitem of an idempotent value
    return a


def phys_zeros(t, capacity: int):
    """Zero device array in a type's physical shape: (capacity,) for
    flat types, (capacity, 2) int64 limb pairs for decimal(>18) (the
    Int128ArrayBlock analogue — types.DataType.lanes)."""
    if t.lanes == 2:
        return jnp.zeros((capacity, 2), dtype=t.dtype)
    return jnp.zeros(capacity, dtype=t.dtype)


def null_column(t, capacity: int, dictionary=None):
    """All-NULL column of any type at a given capacity — outer-join
    padding (the null-RowBlock the reference builds in LookupOuter
    paths). Nested types get structurally-valid empty layouts, not flat
    zero arrays masquerading as lengths."""
    invalid = jnp.zeros(capacity, dtype=jnp.bool_)
    if t.is_array:
        return ArrayColumn(
            t, jnp.zeros(capacity, jnp.int32), invalid, None,
            jnp.zeros(capacity, jnp.int32), null_column(t.element, 16),
        )
    if t.is_map:
        return MapColumn(
            t, jnp.zeros(capacity, jnp.int32), invalid, None,
            jnp.zeros(capacity, jnp.int32),
            null_column(t.key, 16), null_column(t.element, 16),
        )
    if t.is_row:
        return RowColumn(
            t, jnp.zeros(capacity, jnp.int8), invalid, None,
            [null_column(ft, capacity) for _, ft in t.row_fields],
        )
    return Column(t, phys_zeros(t, capacity), invalid, dictionary)


def bucket_capacity(n: int) -> int:
    """Static-shape discipline: round row counts up to a power of two so
    the set of compiled kernel shapes stays small (the analogue of
    Trino's adaptive page sizes without dynamic shapes)."""
    c = MIN_CAPACITY
    while c < n:
        c <<= 1
    return c


class Dictionary:
    """Host-side sorted string dictionary. Device arrays hold int32 codes.

    Values are sorted, so *within one dictionary* code order == lexical
    order, making <, >=, BETWEEN on strings pure int comparisons on
    device. Cross-dictionary operations go through ``unify``.
    """

    __slots__ = ("values", "_index")

    def __init__(self, values: Sequence[str]):
        vals = sorted(set(values))
        self.values: tuple = tuple(vals)
        self._index = {v: i for i, v in enumerate(vals)}

    def __len__(self) -> int:
        return len(self.values)

    def __hash__(self):
        return hash(self.values)

    def __eq__(self, other):
        return isinstance(other, Dictionary) and self.values == other.values

    def code(self, value: str) -> int:
        """Code for value; -1 if absent (compares unequal to everything)."""
        return self._index.get(value, -1)

    def code_lower_bound(self, value: str) -> int:
        """Smallest code whose value >= `value` (for range predicates)."""
        import bisect

        return bisect.bisect_left(self.values, value)

    def encode(self, values: Sequence[str]) -> np.ndarray:
        return np.asarray([self._index[v] for v in values], dtype=np.int32)

    def decode(self, codes: np.ndarray) -> list:
        return [self.values[c] if c >= 0 else None for c in codes]

    @staticmethod
    def unify(a: "Dictionary", b: "Dictionary"):
        """Merged dictionary plus remap arrays old-code -> new-code."""
        merged = Dictionary(a.values + b.values)
        remap_a = np.asarray([merged._index[v] for v in a.values], dtype=np.int32)
        remap_b = np.asarray([merged._index[v] for v in b.values], dtype=np.int32)
        return merged, remap_a, remap_b


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Column:
    """One column: fixed-capacity device array + validity + dictionary."""

    type: T.DataType
    data: jnp.ndarray  # shape (capacity,), dtype = type.dtype
    valid: Optional[jnp.ndarray] = None  # bool (capacity,), None = all valid
    dictionary: Optional[Dictionary] = None

    # -- pytree --
    def tree_flatten(self):
        return (self.data, self.valid), (self.type, self.dictionary)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, valid = children
        return cls(aux[0], data, valid, aux[1])

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    def valid_mask(self) -> jnp.ndarray:
        if self.valid is None:
            return ones_mask(self.data.shape[0])
        return self.valid

    def with_data(self, data, valid="__same__") -> "Column":
        return Column(
            self.type,
            data,
            self.valid if isinstance(valid, str) else valid,
            self.dictionary,
        )

    def gather(self, positions: jnp.ndarray, positions_valid=None) -> "Column":
        """Vectorized position copy — the PositionsAppender analogue
        (main/operator/output/PositionsAppender*.java)."""
        pos = jnp.clip(positions, 0, self.data.shape[0] - 1)
        data = jnp.take(self.data, pos, axis=0)
        valid = None
        if self.valid is not None:
            valid = jnp.take(self.valid, pos)
        if positions_valid is not None:
            valid = positions_valid if valid is None else (valid & positions_valid)
        return Column(self.type, data, valid, self.dictionary)

    # -- host conversion (tests / client protocol) --
    @staticmethod
    def from_numpy(
        type_: T.DataType,
        values: np.ndarray,
        valid: Optional[np.ndarray] = None,
        dictionary: Optional[Dictionary] = None,
        capacity: Optional[int] = None,
    ) -> "Column":
        n = len(values)
        cap = capacity if capacity is not None else bucket_capacity(n)
        shape = (cap, 2) if type_.lanes == 2 else (cap,)
        data = np.zeros(shape, dtype=type_.dtype)
        data[:n] = values
        v = None
        if valid is not None:
            v = np.zeros(cap, dtype=bool)
            v[:n] = valid
        return Column(type_, jnp.asarray(data), None if v is None else jnp.asarray(v), dictionary)

    @staticmethod
    def from_pylist(type_: T.DataType, values: Sequence[Any], capacity=None) -> "Column":
        if type_.kind == T.TypeKind.ARRAY:
            return ArrayColumn.from_pylists(type_.element, values, capacity)
        if type_.kind == T.TypeKind.MAP:
            return MapColumn.from_pydicts(
                type_.key, type_.element, values, capacity
            )
        if type_.kind == T.TypeKind.ROW:
            return RowColumn.from_pytuples(type_, values, capacity)
        has_null = any(v is None for v in values)
        if type_.is_string:
            dictionary = Dictionary([v for v in values if v is not None])
            arr = np.asarray(
                [dictionary.code(v) if v is not None else 0 for v in values],
                dtype=np.int32,
            )
        elif type_.is_decimal:
            dictionary = None
            sf = T.decimal_scale_factor(type_)

            def scaled(v):
                from decimal import Decimal

                if isinstance(v, float):
                    return round(v * sf)
                return int(Decimal(str(v)) * sf)

            if type_.is_long_decimal:
                from trino_tpu.ops.int128 import from_python

                pairs = [
                    from_python(scaled(v)) if v is not None else (0, 0)
                    for v in values
                ]
                arr = np.asarray(pairs, dtype=np.int64).reshape(-1, 2)
            else:
                arr = np.asarray(
                    [scaled(v) if v is not None else 0 for v in values],
                    dtype=type_.dtype,
                )
        else:
            dictionary = None
            fill = 0
            arr = np.asarray(
                [v if v is not None else fill for v in values], dtype=type_.dtype
            )
        valid = None
        if has_null:
            valid = np.asarray([v is not None for v in values], dtype=bool)
        return Column.from_numpy(type_, arr, valid, dictionary, capacity)

    def to_pylist(self, count: Optional[int] = None, live: Optional[np.ndarray] = None):
        data = np.asarray(self.data)
        valid = np.asarray(self.valid) if self.valid is not None else np.ones(len(data), bool)
        if live is not None:
            keep = np.asarray(live)
            data, valid = data[keep], valid[keep]
        if count is not None:
            data, valid = data[:count], valid[:count]
        dict_values = self.dictionary.values if self.dictionary else None
        return decode_values(self.type, data, valid, dict_values)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class ArrayColumn(Column):
    """ARRAY-typed column: per-row views into one flattened element
    column (spi/block/ArrayBlock.java's offsets+values layout, SoA
    form). `data` holds per-row LENGTHS — so generic vectorized code
    that only needs cardinality (the common aggregate/filter case)
    reads an ordinary int32 array — while `starts` + `flat` carry the
    element storage. gather() moves only the per-row views; the flat
    child is shared, never re-laid-out.

    Array columns flow scan -> (filter/project passthrough) -> UNNEST
    within a task, and cross exchanges via the TPG2 nested wire
    encodings (exec/serde.py — offsets + recursively-encoded flat child,
    the ArrayBlockEncoding analogue).
    """

    starts: Optional[jnp.ndarray] = None  # int32 (capacity,)
    flat: Optional[Column] = None  # flattened elements

    def tree_flatten(self):
        return (
            (self.data, self.valid, self.starts, self.flat),
            (self.type, self.dictionary),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, valid, starts, flat = children
        return cls(aux[0], data, valid, aux[1], starts, flat)

    def gather(self, positions: jnp.ndarray, positions_valid=None) -> "ArrayColumn":
        pos = jnp.clip(positions, 0, self.data.shape[0] - 1)
        lengths = jnp.take(self.data, pos)
        starts = jnp.take(self.starts, pos)
        valid = None
        if self.valid is not None:
            valid = jnp.take(self.valid, pos)
        if positions_valid is not None:
            valid = positions_valid if valid is None else (valid & positions_valid)
        return ArrayColumn(
            self.type, lengths, valid, self.dictionary, starts, self.flat
        )

    def with_data(self, data, valid="__same__") -> "ArrayColumn":
        return ArrayColumn(
            self.type,
            data,
            self.valid if isinstance(valid, str) else valid,
            self.dictionary,
            self.starts,
            self.flat,
        )

    @staticmethod
    def from_pylists(element_type: T.DataType, values, capacity=None,
                     dictionary: Optional["Dictionary"] = None) -> "ArrayColumn":
        """values: sequence of python lists (None = NULL array).
        `dictionary`: table-stable element dictionary for string
        elements (keeps plan-time binding valid across batches)."""
        n = len(values)
        cap = capacity if capacity is not None else bucket_capacity(n)
        lengths = np.zeros(cap, dtype=np.int32)
        starts = np.zeros(cap, dtype=np.int32)
        flat_vals: list = []
        valid = None
        if any(v is None for v in values):
            valid = np.zeros(cap, dtype=bool)
        pos = 0
        for i, v in enumerate(values):
            starts[i] = pos
            if v is None:
                continue
            if valid is not None:
                valid[i] = True
            lengths[i] = len(v)
            flat_vals.extend(v)
            pos += len(v)
        if dictionary is not None and element_type.is_string:
            codes = np.asarray(
                [dictionary.code(v) if v is not None else 0 for v in flat_vals],
                dtype=np.int32,
            )
            fvalid = (
                np.asarray([v is not None for v in flat_vals], dtype=bool)
                if any(v is None for v in flat_vals)
                else None
            )
            flat = Column.from_numpy(element_type, codes, fvalid, dictionary)
        else:
            flat = Column.from_pylist(element_type, flat_vals)
        return ArrayColumn(
            T.array_of(element_type),
            jnp.asarray(lengths),
            jnp.asarray(valid) if valid is not None else None,
            None,
            jnp.asarray(starts),
            flat,
        )

    def to_pylist(self, count: Optional[int] = None, live: Optional[np.ndarray] = None):
        lengths = np.asarray(self.data)
        starts = np.asarray(self.starts)
        valid = (
            np.asarray(self.valid)
            if self.valid is not None
            else np.ones(len(lengths), bool)
        )
        flat_vals = self.flat.to_pylist()
        rows = []
        for s, ln, ok in zip(starts, lengths, valid):
            rows.append(
                list(flat_vals[int(s):int(s) + int(ln)]) if ok else None
            )
        if live is not None:
            rows = [r for r, k in zip(rows, np.asarray(live)) if k]
        if count is not None:
            rows = rows[:count]
        return rows


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class MapColumn(Column):
    """MAP-typed column: per-row entry views into two flattened child
    columns (spi/block/MapBlock.java's keys+values layout, SoA form).
    `data` holds per-row entry COUNTS so cardinality() reads an ordinary
    int32 array; `starts` + `flat_keys`/`flat_values` carry the entries.
    gather() moves only the per-row views; the flat children are shared."""

    starts: Optional[jnp.ndarray] = None  # int32 (capacity,)
    flat_keys: Optional[Column] = None
    flat_values: Optional[Column] = None

    def tree_flatten(self):
        return (
            (self.data, self.valid, self.starts, self.flat_keys,
             self.flat_values),
            (self.type, self.dictionary),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, valid, starts, fk, fv = children
        return cls(aux[0], data, valid, aux[1], starts, fk, fv)

    def gather(self, positions: jnp.ndarray, positions_valid=None) -> "MapColumn":
        pos = jnp.clip(positions, 0, self.data.shape[0] - 1)
        lengths = jnp.take(self.data, pos)
        starts = jnp.take(self.starts, pos)
        valid = None
        if self.valid is not None:
            valid = jnp.take(self.valid, pos)
        if positions_valid is not None:
            valid = positions_valid if valid is None else (valid & positions_valid)
        return MapColumn(
            self.type, lengths, valid, self.dictionary, starts,
            self.flat_keys, self.flat_values,
        )

    def with_data(self, data, valid="__same__") -> "MapColumn":
        return MapColumn(
            self.type,
            data,
            self.valid if isinstance(valid, str) else valid,
            self.dictionary,
            self.starts,
            self.flat_keys,
            self.flat_values,
        )

    @staticmethod
    def from_pydicts(key_type: T.DataType, value_type: T.DataType, values,
                     capacity=None) -> "MapColumn":
        """values: sequence of python dicts (None = NULL map)."""
        n = len(values)
        cap = capacity if capacity is not None else bucket_capacity(n)
        lengths = np.zeros(cap, dtype=np.int32)
        starts = np.zeros(cap, dtype=np.int32)
        fk: list = []
        fv: list = []
        valid = None
        if any(v is None for v in values):
            valid = np.zeros(cap, dtype=bool)
        pos = 0
        for i, v in enumerate(values):
            starts[i] = pos
            if v is None:
                continue
            if valid is not None:
                valid[i] = True
            lengths[i] = len(v)
            for k, x in v.items():
                fk.append(k)
                fv.append(x)
            pos += len(v)
        return MapColumn(
            T.map_of(key_type, value_type),
            jnp.asarray(lengths),
            jnp.asarray(valid) if valid is not None else None,
            None,
            jnp.asarray(starts),
            Column.from_pylist(key_type, fk),
            Column.from_pylist(value_type, fv),
        )

    def to_pylist(self, count: Optional[int] = None, live: Optional[np.ndarray] = None):
        lengths = np.asarray(self.data)
        starts = np.asarray(self.starts)
        valid = (
            np.asarray(self.valid)
            if self.valid is not None
            else np.ones(len(lengths), bool)
        )
        ks = self.flat_keys.to_pylist()
        vs = self.flat_values.to_pylist()
        rows = []
        for s, ln, ok in zip(starts, lengths, valid):
            if not ok:
                rows.append(None)
            else:
                s, ln = int(s), int(ln)
                rows.append(dict(zip(ks[s:s + ln], vs[s:s + ln])))
        if live is not None:
            rows = [r for r, k in zip(rows, np.asarray(live)) if k]
        if count is not None:
            rows = rows[:count]
        return rows


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class RowColumn(Column):
    """ROW-typed column: parallel child columns, one per field
    (spi/block/RowBlock.java). `data` is a per-row presence byte (int8 1)
    so generic code sees an ordinary array; NULL rows ride `valid`."""

    children: Optional[list] = None  # list[Column], same capacity

    def tree_flatten(self):
        return (
            (self.data, self.valid, self.children),
            (self.type, self.dictionary),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, valid, kids = children
        return cls(aux[0], data, valid, aux[1], list(kids))

    def gather(self, positions: jnp.ndarray, positions_valid=None) -> "RowColumn":
        pos = jnp.clip(positions, 0, self.data.shape[0] - 1)
        data = jnp.take(self.data, pos, axis=0)
        valid = None
        if self.valid is not None:
            valid = jnp.take(self.valid, pos)
        if positions_valid is not None:
            valid = positions_valid if valid is None else (valid & positions_valid)
        return RowColumn(
            self.type, data, valid, self.dictionary,
            [c.gather(positions, positions_valid) for c in self.children],
        )

    def with_data(self, data, valid="__same__") -> "RowColumn":
        return RowColumn(
            self.type,
            data,
            self.valid if isinstance(valid, str) else valid,
            self.dictionary,
            self.children,
        )

    @staticmethod
    def from_pytuples(row_type: T.DataType, values, capacity=None) -> "RowColumn":
        """values: sequence of python tuples/lists (None = NULL row)."""
        n = len(values)
        cap = capacity if capacity is not None else bucket_capacity(n)
        presence = np.zeros(cap, dtype=np.int8)
        presence[:n] = 1
        valid = None
        if any(v is None for v in values):
            valid = np.zeros(cap, dtype=bool)
            for i, v in enumerate(values):
                valid[i] = v is not None
        kids = []
        for fi, (_, ft) in enumerate(row_type.row_fields):
            kids.append(
                Column.from_pylist(
                    ft,
                    [None if v is None else v[fi] for v in values],
                    capacity=cap,
                )
            )
        return RowColumn(
            row_type,
            jnp.asarray(presence),
            jnp.asarray(valid) if valid is not None else None,
            None,
            kids,
        )

    def to_pylist(self, count: Optional[int] = None, live: Optional[np.ndarray] = None):
        valid = (
            np.asarray(self.valid)
            if self.valid is not None
            else np.ones(self.capacity, bool)
        )
        kid_vals = [c.to_pylist() for c in self.children]
        rows = []
        for i in range(self.capacity):
            rows.append(
                tuple(kv[i] for kv in kid_vals) if valid[i] else None
            )
        if live is not None:
            rows = [r for r, k in zip(rows, np.asarray(live)) if k]
        if count is not None:
            rows = rows[:count]
        return rows


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class RelBatch:
    """A batch of rows: columns share capacity; `live` masks real rows.

    The Page analogue. ``live=None`` means all `capacity` rows are live
    (the common full-batch fast path, like a Page with no mask).
    """

    columns: list  # list[Column]
    live: Optional[jnp.ndarray] = None  # bool (capacity,)

    def tree_flatten(self):
        return (self.columns, self.live), (len(self.columns),)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(list(children[0]), children[1])

    @property
    def capacity(self) -> int:
        return self.columns[0].capacity if self.columns else 0

    @property
    def width(self) -> int:
        return len(self.columns)

    def live_mask(self) -> jnp.ndarray:
        if self.live is None:
            return ones_mask(self.capacity)
        return self.live

    def row_count(self) -> int:
        """Host-synced live-row count (test/protocol use; kernels use masks)."""
        if self.live is None:
            return self.capacity
        return int(jnp.sum(self.live))

    def column(self, i: int) -> Column:
        return self.columns[i]

    def with_columns(self, columns, live="__same__") -> "RelBatch":
        return RelBatch(list(columns), self.live if isinstance(live, str) else live)

    def mask(self, keep: jnp.ndarray) -> "RelBatch":
        """Filter: AND `keep` into the live mask (no data movement)."""
        live = keep if self.live is None else (self.live & keep)
        return RelBatch(self.columns, live)

    def gather(self, positions: jnp.ndarray, positions_live=None) -> "RelBatch":
        """Batch-wide position copy. Random gathers cost ~16 device
        cycles PER ELEMENT on TPU (measured r4: 16.5ms/M for int64,
        index pattern irrelevant), so the validity masks of all flat
        columns are packed into ONE int32 bitmask and gathered once
        instead of one bool gather per nullable column."""
        flat_nullable = [
            i for i, c in enumerate(self.columns)
            if c.valid is not None and not c.type.is_nested
            # consolidation paths carry mixed-capacity columns; only
            # full-capacity ones can share the packed mask + positions
            and c.data.shape[0] == self.capacity
            and c.valid.shape[0] == self.capacity
        ]
        if len(flat_nullable) < 2 or len(flat_nullable) > 32:
            cols = [c.gather(positions) for c in self.columns]
            return RelBatch(cols, positions_live)
        pos = jnp.clip(positions, 0, self.capacity - 1)
        bitpos = {i: k for k, i in enumerate(flat_nullable)}
        bits = None
        for i, k in bitpos.items():
            b = self.columns[i].valid.astype(jnp.int32) << k
            bits = b if bits is None else (bits | b)
        gbits = jnp.take(bits, pos)
        cols = []
        for i, c in enumerate(self.columns):
            k = bitpos.get(i)
            if k is not None:
                data = jnp.take(c.data, pos, axis=0)
                valid = (gbits >> k) & 1 != 0
                cols.append(Column(c.type, data, valid, c.dictionary))
            else:
                cols.append(c.gather(positions))
        return RelBatch(cols, positions_live)

    def compact(self) -> "RelBatch":
        """Front-pack live rows (stable) — Page.compact analogue
        (spi/Page.java:180). Output capacity unchanged; dead tail rows
        get live=False. Pure vectorized: stable argsort on ~live."""
        if self.live is None:
            return self
        order = jnp.argsort(~self.live, stable=True)
        n_live = jnp.sum(self.live)
        idx = jnp.arange(self.capacity)
        new_live = idx < n_live
        cols = [c.gather(order) for c in self.columns]
        return RelBatch(cols, new_live)

    def select(self, indices: Sequence[int]) -> "RelBatch":
        return RelBatch([self.columns[i] for i in indices], self.live)

    # -- host conversion --
    @staticmethod
    def from_pydict(schema, data: dict, capacity=None) -> "RelBatch":
        """schema: list[(name, DataType)] — names are positional only."""
        n = None
        cols = []
        for name, typ in schema:
            vals = data[name]
            n = len(vals) if n is None else n
            assert len(vals) == n
        cap = capacity if capacity is not None else bucket_capacity(n or 0)
        for name, typ in schema:
            cols.append(Column.from_pylist(typ, data[name], capacity=cap))
        live = None
        if (n or 0) != cap:
            lv = np.zeros(cap, dtype=bool)
            lv[: n or 0] = True
            live = jnp.asarray(lv)
        return RelBatch(cols, live)

    def to_pylists(self):
        """Rows as list of python lists, live rows only, in order. The
        whole batch moves device->host in ONE transfer (remote devices
        pay a round trip per fetch)."""
        host = jax.device_get(self)
        live = None
        if host.live is not None:
            live = np.asarray(host.live)
        cols = [c.to_pylist(live=live) for c in host.columns]
        return [list(row) for row in zip(*cols)] if cols else []


def decode_values(type_: T.DataType, data, valid, dict_values) -> list:
    """Physical values -> python values (the single host-side decode rule
    set, shared by Column.to_pylist and the wire-page protocol decode)."""
    out = []
    for x, ok in zip(data, valid):
        if not ok:
            out.append(None)
        elif type_.is_string:
            out.append(dict_values[int(x)] if dict_values else str(int(x)))
        elif type_.is_decimal:
            if type_.is_long_decimal:
                from trino_tpu.ops.int128 import to_python

                v = to_python(int(x[0]), int(x[1]))
                out.append(v / T.decimal_scale_factor(type_))
            else:
                out.append(int(x) / T.decimal_scale_factor(type_))
        elif type_.kind == T.TypeKind.BOOLEAN:
            out.append(bool(x))
        elif type_.is_floating:
            out.append(float(x))
        elif type_.kind == T.TypeKind.TIMESTAMP_TZ:
            from trino_tpu.ops.tz import format_tstz

            out.append(format_tstz(int(x)))
        else:
            out.append(int(x))
    return out


def unify_column_dicts(cols: Sequence[Column]) -> list:
    """Remap a set of same-type string columns onto one merged dictionary
    (no-op when dictionaries already agree, the table-stable fast path)."""
    dicts = [c.dictionary for c in cols]
    present = [d for d in dicts if d is not None]
    if not present or all(d == present[0] for d in dicts if d is not None):
        return list(cols)
    merged = present[0]
    for d in present[1:]:
        merged, _, _ = Dictionary.unify(merged, d)
    out = []
    for c in cols:
        if c.dictionary is None or c.dictionary == merged:
            out.append(Column(c.type, c.data, c.valid, merged))
            continue
        remap = jnp.asarray(
            [merged.code(v) for v in c.dictionary.values], dtype=jnp.int32
        )
        data = jnp.take(remap, jnp.clip(c.data, 0, max(len(c.dictionary) - 1, 0)))
        out.append(Column(c.type, data, c.valid, merged))
    return out


def _concat_valid(parts):
    if any(p.valid is not None for p in parts):
        return jnp.concatenate(
            [
                p.valid
                if p.valid is not None
                else jnp.ones(p.data.shape[0], dtype=jnp.bool_)
                for p in parts
            ]
        )
    return None


def _concat_columns(parts: list):
    """Concatenate column fragments of one schema slot, preserving
    NESTED layouts: array/map flats concatenate with starts rebased by
    the preceding flats' capacities; row children concatenate
    recursively. (A plain data-concat would splice per-row LENGTHS and
    drop the element stores.)"""
    first = parts[0]
    if isinstance(first, (ArrayColumn, MapColumn)):
        data = jnp.concatenate([p.data for p in parts])
        valid = _concat_valid(parts)
        starts = []
        off = 0
        flats1 = []
        flats2 = []
        for p in parts:
            starts.append(p.starts + off)
            if isinstance(p, ArrayColumn):
                off += p.flat.capacity
                flats1.append(p.flat)
            else:
                off += p.flat_keys.capacity
                flats1.append(p.flat_keys)
                flats2.append(p.flat_values)
        starts = jnp.concatenate(starts)
        if isinstance(first, ArrayColumn):
            return ArrayColumn(
                first.type, data, valid, None, starts,
                _concat_columns(flats1),
            )
        return MapColumn(
            first.type, data, valid, None, starts,
            _concat_columns(flats1), _concat_columns(flats2),
        )
    if isinstance(first, RowColumn):
        data = jnp.concatenate([p.data for p in parts])
        valid = _concat_valid(parts)
        kids = [
            _concat_columns([p.children[i] for p in parts])
            for i in range(len(first.children))
        ]
        return RowColumn(first.type, data, valid, None, kids)
    parts = unify_column_dicts(parts)
    data = jnp.concatenate([p.data for p in parts])
    return Column(parts[0].type, data, _concat_valid(parts), parts[0].dictionary)


def concat_batches(batches: Sequence["RelBatch"]) -> "RelBatch":
    """Concatenate batches (PagesIndex-style consolidation —
    main/operator/PagesIndex.java:80 addPage). Output capacity is the sum
    of input capacities (already powers of two stay bucketed enough)."""
    batches = list(batches)
    if len(batches) == 1:
        return batches[0]
    width = batches[0].width
    cols = [
        _concat_columns([b.columns[i] for b in batches])
        for i in range(width)
    ]
    live = jnp.concatenate([b.live_mask() for b in batches])
    return RelBatch(cols, live)


class RuntimeDictionary(Dictionary):
    """Plan-time placeholder for a string column whose dictionary is
    created at EXECUTION time (listagg output: the aggregate builds new
    strings). Pure column references pass the runtime dictionary
    through (operators.make_filter_project_fn); any plan-time-bound
    string operation cannot know the values and must fail loudly at
    bind time rather than treat the column as all-NULL."""

    def __init__(self):
        super().__init__([])
