"""Security: identity, authentication, access control.

Analogue of the reference's security surface (SURVEY.md §2.10):
authenticators under main/server/security/ (password/JWT/insecure) and
the AccessControl SPI (spi/security/ + main/security/) with the
file-based rules plugin (plugin/trino-file-based-access-control
semantics: ordered rules, first match wins, no match denies).

Authenticators run in the coordinator HTTP front (runtime/server.py);
AccessControl checks run in the engine at statement boundaries against
the tables the plan actually reads/writes.
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import hmac
import json
import re
import time
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class Identity:
    """spi/security/Identity analogue."""

    user: str
    groups: Tuple[str, ...] = ()


class AccessDeniedError(Exception):
    pass


class AuthenticationError(Exception):
    pass


# ---------------------------------------------------------------------------
# Access control (spi/security/SystemAccessControl analogue)
# ---------------------------------------------------------------------------


class AccessControl:
    """Every check raises AccessDeniedError on denial."""

    def check_can_execute_query(self, identity: Identity) -> None:
        pass

    def check_can_select(
        self, identity: Identity, catalog: str, schema: str, table: str,
        columns: Sequence[str] = (),
    ) -> None:
        pass

    def check_can_insert(
        self, identity: Identity, catalog: str, schema: str, table: str
    ) -> None:
        pass

    def check_can_delete(
        self, identity: Identity, catalog: str, schema: str, table: str
    ) -> None:
        pass

    def check_can_update(
        self, identity: Identity, catalog: str, schema: str, table: str
    ) -> None:
        pass

    def check_can_create_table(
        self, identity: Identity, catalog: str, schema: str, table: str
    ) -> None:
        pass

    def check_can_drop_table(
        self, identity: Identity, catalog: str, schema: str, table: str
    ) -> None:
        pass

    def check_can_set_session_property(
        self, identity: Identity, name: str
    ) -> None:
        pass


class AllowAllAccessControl(AccessControl):
    """Default (main/security/AllowAllAccessControl analogue)."""


PRIVILEGES = ("SELECT", "INSERT", "DELETE", "UPDATE", "OWNERSHIP")


@dataclasses.dataclass(frozen=True)
class TableRule:
    """One file-based rule: regex match on user/catalog/schema/table,
    granting a privilege set. Missing patterns match everything."""

    privileges: Tuple[str, ...]
    user: str = ".*"
    catalog: str = ".*"
    schema: str = ".*"
    table: str = ".*"

    def matches(self, identity: Identity, catalog, schema, table) -> bool:
        return (
            re.fullmatch(self.user, identity.user) is not None
            and re.fullmatch(self.catalog, catalog) is not None
            and re.fullmatch(self.schema, schema) is not None
            and re.fullmatch(self.table, table) is not None
        )


class FileBasedAccessControl(AccessControl):
    """Ordered-rules access control: FIRST matching rule decides; no
    match denies (the reference's file-based table rules)."""

    def __init__(self, rules: Sequence[dict] | Sequence[TableRule]):
        self.rules: List[TableRule] = [
            r if isinstance(r, TableRule) else TableRule(
                tuple(p.upper() for p in r.get("privileges", ())),
                r.get("user", ".*"),
                r.get("catalog", ".*"),
                r.get("schema", ".*"),
                r.get("table", ".*"),
            )
            for r in rules
        ]

    @classmethod
    def from_file(cls, path: str) -> "FileBasedAccessControl":
        with open(path) as f:
            doc = json.load(f)
        return cls(doc.get("tables", []))

    def _check(self, privilege: str, identity, catalog, schema, table):
        for rule in self.rules:
            if rule.matches(identity, catalog, schema, table):
                if privilege in rule.privileges or "OWNERSHIP" in rule.privileges:
                    return
                break  # first match decides
        raise AccessDeniedError(
            f"Access Denied: {identity.user} cannot {privilege} "
            f"{catalog}.{schema}.{table}"
        )

    def check_can_select(self, identity, catalog, schema, table, columns=()):
        self._check("SELECT", identity, catalog, schema, table)

    def check_can_insert(self, identity, catalog, schema, table):
        self._check("INSERT", identity, catalog, schema, table)

    def check_can_delete(self, identity, catalog, schema, table):
        self._check("DELETE", identity, catalog, schema, table)

    def check_can_update(self, identity, catalog, schema, table):
        self._check("UPDATE", identity, catalog, schema, table)

    def check_can_create_table(self, identity, catalog, schema, table):
        self._check("OWNERSHIP", identity, catalog, schema, table)

    def check_can_drop_table(self, identity, catalog, schema, table):
        self._check("OWNERSHIP", identity, catalog, schema, table)


# ---------------------------------------------------------------------------
# Authenticators (main/server/security/ analogues)
# ---------------------------------------------------------------------------


class Authenticator:
    def authenticate(self, headers: Dict[str, str]) -> Identity:
        raise NotImplementedError


class InsecureAuthenticator(Authenticator):
    """Trusts X-Trino-User (the reference's insecure default for
    unauthenticated HTTP)."""

    def authenticate(self, headers) -> Identity:
        return Identity(headers.get("X-Trino-User", "anonymous"))


class PasswordAuthenticator(Authenticator):
    """HTTP Basic over a salted-hash password map
    (password-file authenticator analogue). Store entries made with
    hash_password(); plaintext never lives in memory at check time."""

    def __init__(self, users: Dict[str, str]):
        """users: user -> pbkdf2$<iters>$<salt>$<hex> (see hash_password;
        legacy salt$sha256hex entries still verify)."""
        self.users = dict(users)

    @staticmethod
    def hash_password(password: str, salt: Optional[str] = None,
                      iterations: int = 100_000) -> str:
        """PBKDF2-HMAC-SHA256 with a per-user random salt (the
        reference's password-file authenticator uses bcrypt/PBKDF2;
        one unsalted SHA-256 round is brute-forceable and makes equal
        passwords visibly equal across users)."""
        import secrets

        if salt is None:
            salt = secrets.token_hex(16)
        digest = hashlib.pbkdf2_hmac(
            "sha256", password.encode(), salt.encode(), iterations
        ).hex()
        return f"pbkdf2${iterations}${salt}${digest}"

    def authenticate(self, headers) -> Identity:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Basic "):
            raise AuthenticationError("missing Basic credentials")
        try:
            user, _, password = (
                base64.b64decode(auth[6:]).decode().partition(":")
            )
        except Exception as ex:
            raise AuthenticationError("malformed Basic credentials") from ex
        stored = self.users.get(user)
        if stored is None:
            raise AuthenticationError("unknown user")
        parts = stored.split("$")
        if parts[0] == "pbkdf2" and len(parts) == 4:
            _, iters, salt, digest = parts
            expect = hashlib.pbkdf2_hmac(
                "sha256", password.encode(), salt.encode(), int(iters)
            ).hex()
        else:  # legacy salt$sha256hex entries
            salt, _, digest = stored.partition("$")
            expect = hashlib.sha256((salt + password).encode()).hexdigest()
        if not hmac.compare_digest(expect, digest):
            raise AuthenticationError("bad password")
        return Identity(user)


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_dec(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


class InternalAuthenticator:
    """Shared-secret authentication for engine-internal HTTP (the
    InternalAuthenticationManager analogue): workers and coordinators
    exchange an HS256 JWT in X-Trino-Internal-Bearer. Tokens are minted
    short-lived and re-minted on expiry."""

    HEADER = "X-Trino-Internal-Bearer"

    def __init__(self, secret: str):
        self._jwt = JwtAuthenticator(secret)
        self._token: Optional[str] = None
        self._token_exp = 0.0

    def token(self) -> str:
        now = time.time()
        if self._token is None or now > self._token_exp - 30:
            self._token = self._jwt.issue("trino-internal", ttl_seconds=300)
            self._token_exp = now + 300
        return self._token

    def verify(self, headers) -> None:
        """Raises AuthenticationError when the internal bearer is
        missing or invalid."""
        tok = headers.get(self.HEADER, "")
        if not tok:
            raise AuthenticationError("missing internal bearer")
        self._jwt.authenticate({"Authorization": f"Bearer {tok}"})


class JwtAuthenticator(Authenticator):
    """Bearer JWT with HS256 (the reference's JWT authenticator reduced
    to the shared-secret HMAC form — no external crypto deps)."""

    def __init__(self, secret: str, principal_claim: str = "sub"):
        self.secret = secret.encode()
        self.principal_claim = principal_claim

    def issue(self, user: str, ttl_seconds: int = 3600) -> str:
        header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        payload = _b64url(
            json.dumps(
                {self.principal_claim: user,
                 "exp": int(time.time()) + ttl_seconds}
            ).encode()
        )
        signing_input = f"{header}.{payload}".encode()
        sig = _b64url(hmac.new(self.secret, signing_input, hashlib.sha256).digest())
        return f"{header}.{payload}.{sig}"

    def authenticate(self, headers) -> Identity:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            raise AuthenticationError("missing Bearer token")
        token = auth[7:]
        try:
            header_b64, payload_b64, sig_b64 = token.split(".")
            signing_input = f"{header_b64}.{payload_b64}".encode()
            expect = hmac.new(
                self.secret, signing_input, hashlib.sha256
            ).digest()
            if not hmac.compare_digest(expect, _b64url_dec(sig_b64)):
                raise AuthenticationError("bad signature")
            payload = json.loads(_b64url_dec(payload_b64))
        except AuthenticationError:
            raise
        except Exception as ex:
            raise AuthenticationError("malformed token") from ex
        if payload.get("exp") is not None and payload["exp"] < time.time():
            raise AuthenticationError("token expired")
        user = payload.get(self.principal_claim)
        if not user:
            raise AuthenticationError("no principal claim")
        return Identity(str(user))
