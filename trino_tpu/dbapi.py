"""DB-API 2.0 (PEP 249) driver over the statement protocol.

The trino-jdbc analogue (client/trino-jdbc/.../TrinoDriver.java:21 —
SURVEY.md §2.11): the standard database driver interface of the host
language, layered on the polling HTTP client exactly as the JDBC
driver layers on StatementClientV1. Supports qmark parameter binding
by literal substitution (the protocol is text-based, as in the
reference's non-prepared path), Basic and Bearer authentication.

    import trino_tpu.dbapi as dbapi
    conn = dbapi.connect("http://127.0.0.1:8080", user="alice")
    cur = conn.cursor()
    cur.execute("SELECT n_name FROM nation WHERE n_nationkey = ?", (3,))
    print(cur.fetchall())
"""

from __future__ import annotations

import base64
import datetime
from typing import Iterable, List, Optional, Sequence

from trino_tpu.client import Client, QueryError

apilevel = "2.0"
threadsafety = 1  # threads may share the module, not connections
paramstyle = "qmark"


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class ProgrammingError(DatabaseError):
    pass


class OperationalError(DatabaseError):
    pass


def _quote_param(value) -> str:
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return repr(value)
    if isinstance(value, datetime.date) and not isinstance(
        value, datetime.datetime
    ):
        return f"date '{value.isoformat()}'"
    if isinstance(value, str):
        return "'" + value.replace("'", "''") + "'"
    raise ProgrammingError(f"cannot bind parameter of type {type(value).__name__}")


def _substitute(sql: str, params: Sequence) -> str:
    """qmark substitution, skipping '?' inside string literals,
    double-quoted identifiers, and -- / block comments."""
    out: List[str] = []
    it = iter(params)
    i = 0
    n = len(sql)
    while i < n:
        c = sql[i]
        if c == "'" or c == '"':
            q = c
            j = i + 1
            while j < n:
                if sql[j] == q and j + 1 < n and sql[j + 1] == q:
                    j += 2
                    continue
                if sql[j] == q:
                    break
                j += 1
            out.append(sql[i:j + 1])
            i = j + 1
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":
            j = sql.find("\n", i)
            j = n if j < 0 else j
            out.append(sql[i:j])
            i = j
            continue
        if c == "/" and i + 1 < n and sql[i + 1] == "*":
            j = sql.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append(sql[i:j])
            i = j
            continue
        if c == "?":
            try:
                out.append(_quote_param(next(it)))
            except StopIteration:
                raise ProgrammingError("not enough parameters") from None
            i += 1
            continue
        out.append(c)
        i += 1
    remaining = sum(1 for _ in it)
    if remaining:
        raise ProgrammingError(f"{remaining} unused parameters")
    return "".join(out)


class Cursor:
    arraysize = 1

    def __init__(self, connection: "Connection"):
        self.connection = connection
        self.description: Optional[List[tuple]] = None
        self.rowcount = -1
        self._rows: List[list] = []
        self._pos = 0
        self._closed = False

    def _check(self):
        if self._closed or self.connection._closed:
            raise InterfaceError("cursor is closed")

    def execute(self, operation: str, parameters: Sequence = ()) -> "Cursor":
        self._check()
        if parameters:
            # server-side parameter binding (VERDICT r3 item #8): ship
            # the statement once via the prepared-statement protocol
            # headers and EXECUTE ... USING with literal parameters —
            # no client-side string interpolation of the query body
            client = getattr(self.connection, "_client", None)
            if client is not None and hasattr(client, "prepared"):
                name = "stmt"
                client.prepared[name] = operation
                lits = ", ".join(_quote_param(p) for p in parameters)
                operation = f"EXECUTE {name} USING {lits}"
            else:
                operation = _substitute(operation, list(parameters))
        try:
            result = self.connection._execute(operation)
        except QueryError as ex:
            raise DatabaseError(str(ex)) from ex
        self.description = [
            (c["name"], c.get("type"), None, None, None, None, None)
            for c in result.columns
        ]
        self._rows = result.rows
        self._pos = 0
        self.rowcount = len(self._rows)
        return self

    def executemany(self, operation: str, seq_of_parameters: Iterable[Sequence]):
        for p in seq_of_parameters:
            self.execute(operation, p)
        return self

    def fetchone(self) -> Optional[list]:
        self._check()
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None) -> List[list]:
        self._check()
        size = size or self.arraysize
        out = self._rows[self._pos:self._pos + size]
        self._pos += len(out)
        return out

    def fetchall(self) -> List[list]:
        self._check()
        out = self._rows[self._pos:]
        self._pos = len(self._rows)
        return out

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row

    def close(self):
        self._closed = True


class Connection:
    """One protocol session. commit()/rollback() issue the transaction
    statements when autocommit is off (PEP 249 transaction model)."""

    def __init__(self, client_or_uri, user=None, password=None, token=None,
                 autocommit=True, timeout: float = 120.0):
        if isinstance(client_or_uri, str):
            headers = {}
            if token is not None:
                headers["Authorization"] = f"Bearer {token}"
            elif password is not None:
                cred = base64.b64encode(
                    f"{user}:{password}".encode()
                ).decode()
                headers["Authorization"] = f"Basic {cred}"
            elif user is not None:
                headers["X-Trino-User"] = user
            self._client = Client(
                client_or_uri, timeout=timeout, headers=headers
            )
        else:
            self._client = client_or_uri
        self.autocommit = autocommit
        self._closed = False
        self._in_txn = False

    def _execute(self, sql: str):
        if not self.autocommit and not self._in_txn:
            self._client.execute("START TRANSACTION")
            self._in_txn = True
        return self._client.execute(sql)

    def cursor(self) -> Cursor:
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self)

    def _clear_txn(self):
        self._in_txn = False
        if hasattr(self._client, "transaction_id"):
            self._client.transaction_id = None

    def _end_txn(self, sql: str):
        """Issue COMMIT/ROLLBACK. A SERVER-reported failure still prunes
        the server-side transaction, so local state must clear too or
        every later statement wedges on a dead id. A TRANSPORT failure
        (the statement may never have reached the server) keeps local
        state so the application can retry."""
        try:
            self._client.execute(sql)
        except QueryError:
            self._clear_txn()
            raise
        else:
            self._clear_txn()

    def commit(self):
        if self._in_txn:
            self._end_txn("COMMIT")

    def rollback(self):
        if self._in_txn:
            self._end_txn("ROLLBACK")

    def close(self):
        if self._in_txn:
            try:
                self.rollback()
            except Exception:
                pass
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def connect(uri: str, user: Optional[str] = None,
            password: Optional[str] = None, token: Optional[str] = None,
            autocommit: bool = True, timeout: float = 120.0) -> Connection:
    return Connection(uri, user=user, password=password, token=token,
                      autocommit=autocommit, timeout=timeout)
