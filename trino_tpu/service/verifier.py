"""Query verifier: replay a query set against two engines, compare.

service/trino-verifier analogue (4.6k LoC in the reference): runs each
query on a control and a test target, compares row sets (order-
insensitive unless the query has a top-level ORDER BY, with float
tolerance), and reports per-query verdicts — the tool the reference
uses to validate a new build against production."""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Callable, List, Optional, Sequence


@dataclasses.dataclass
class VerifierResult:
    name: str
    status: str  # "match" | "mismatch" | "control_error" | "test_error"
    control_seconds: float = 0.0
    test_seconds: float = 0.0
    detail: str = ""


def _normalize(rows: Sequence[Sequence], float_tol: float):
    out = []
    for row in rows:
        norm = []
        for v in row:
            if isinstance(v, float):
                if math.isnan(v):
                    norm.append("NaN")
                else:
                    # bucket to tolerance so sort keys agree across engines
                    norm.append(round(v, 6) if float_tol else v)
            else:
                norm.append(v)
        out.append(tuple(norm))
    return out


def _rows_equal(a, b, ordered: bool, float_tol: float) -> Optional[str]:
    if len(a) != len(b):
        return f"row count {len(a)} != {len(b)}"
    ka, kb = _normalize(a, float_tol), _normalize(b, float_tol)
    if not ordered:
        key = repr
        ka = sorted(ka, key=key)
        kb = sorted(kb, key=key)
    for i, (ra, rb) in enumerate(zip(ka, kb)):
        if len(ra) != len(rb):
            return f"row {i}: column count differs"
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and isinstance(vb, float):
                if abs(va - vb) > float_tol * max(1.0, abs(va), abs(vb)):
                    return f"row {i}: {va!r} != {vb!r}"
            elif va != vb:
                return f"row {i}: {va!r} != {vb!r}"
    return None


def _has_top_level_order_by(sql: str) -> bool:
    """Row order is only deterministic with a TOP-LEVEL ORDER BY;
    'order by' in a subquery (or a string literal) does not count, so
    ask the parser rather than substring-matching."""
    try:
        from trino_tpu.sql.parser import parse

        stmt = parse(sql)
        return bool(getattr(stmt, "order_by", ()))
    except Exception:
        return "order by" in sql.lower()  # non-engine dialects


class Verifier:
    """control/test are callables sql -> rows (e.g. runner.execute(...)
    adapted, or a dbapi cursor) so any engine pairing works."""

    def __init__(
        self,
        control: Callable[[str], Sequence[Sequence]],
        test: Callable[[str], Sequence[Sequence]],
        float_tol: float = 1e-6,
    ):
        self.control = control
        self.test = test
        self.float_tol = float_tol

    def verify(self, name: str, sql: str) -> VerifierResult:
        t0 = time.perf_counter()
        try:
            control_rows = self.control(sql)
        except Exception as ex:
            return VerifierResult(
                name, "control_error", detail=f"{type(ex).__name__}: {ex}"[:300]
            )
        t1 = time.perf_counter()
        try:
            test_rows = self.test(sql)
        except Exception as ex:
            return VerifierResult(
                name, "test_error", t1 - t0,
                detail=f"{type(ex).__name__}: {ex}"[:300],
            )
        t2 = time.perf_counter()
        diff = _rows_equal(
            control_rows, test_rows, _has_top_level_order_by(sql),
            self.float_tol,
        )
        return VerifierResult(
            name,
            "match" if diff is None else "mismatch",
            t1 - t0,
            t2 - t1,
            diff or "",
        )

    def verify_suite(self, queries: dict) -> List[VerifierResult]:
        return [self.verify(name, sql) for name, sql in queries.items()]


def runner_target(runner) -> Callable[[str], Sequence[Sequence]]:
    """Adapt a LocalQueryRunner/DistributedQueryRunner."""
    return lambda sql: runner.execute(sql).rows


def client_target(client) -> Callable[[str], Sequence[Sequence]]:
    """Adapt a trino_tpu.client.Client (HTTP)."""
    return lambda sql: client.execute(sql).rows
