"""Auxiliary services: proxy and verifier (the reference's service/
top-level modules — SURVEY.md §2.11: trino-proxy, trino-verifier)."""
