"""HTTP proxy fronting one or more coordinators.

service/trino-proxy analogue (913 LoC in the reference): accepts the
client statement protocol, forwards to a backend coordinator chosen
round-robin per NEW query, and rewrites nextUri links so the client
keeps polling through the proxy. Follow-up polls route to the backend
that owns the query (sticky by query id)."""

from __future__ import annotations

import json
import threading
from trino_tpu.analysis import threadreg
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List


class ProxyServer:
    def __init__(self, backend_uris: List[str], port: int = 0):
        self.backends = [u.rstrip("/") for u in backend_uris]
        assert self.backends, "proxy needs at least one backend"
        self._rr = 0
        self._owner: Dict[str, str] = {}  # query id -> backend uri
        self._lock = named_lock("ProxyServer._lock")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _forward(self, backend: str, body: bytes | None):
                req = urllib.request.Request(
                    backend + self.path,
                    data=body,
                    method=self.command,
                    headers={
                        k: v
                        for k, v in self.headers.items()
                        if k.lower() not in ("host", "content-length")
                    },
                )
                try:
                    with urllib.request.urlopen(req, timeout=300) as r:
                        ctype = r.headers.get(
                            "Content-Type", "application/json"
                        )
                        return r.status, r.read(), ctype
                except urllib.error.HTTPError as e:
                    return (
                        e.code, e.read(),
                        e.headers.get("Content-Type", "application/json"),
                    )

            def _respond(self, code: int, payload: bytes, ctype: str,
                         backend: str):
                # rewrite nextUri to keep the client pointed at the proxy
                if "json" in ctype:
                    try:
                        doc = json.loads(payload)
                        if isinstance(doc, dict) and doc.get("nextUri"):
                            doc["nextUri"] = doc["nextUri"].replace(
                                backend, outer.uri
                            )
                            if doc.get("id"):
                                outer._remember(doc["id"], backend)
                        if (
                            isinstance(doc, dict)
                            and doc.get("nextUri") is None
                            and doc.get("id")
                        ):
                            outer._forget(doc["id"])
                        payload = json.dumps(doc).encode()
                    except Exception:
                        pass
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _backend_for_path(self) -> str:
                parts = [p for p in self.path.split("/") if p]
                if len(parts) >= 4 and parts[:3] == [
                    "v1", "statement", "executing",
                ]:
                    with outer._lock:
                        owner = outer._owner.get(parts[3])
                    if owner:
                        return owner
                with outer._lock:
                    outer._rr = (outer._rr + 1) % len(outer.backends)
                    return outer.backends[outer._rr]

            def do_POST(self):
                ln = int(self.headers.get("Content-Length", "0") or 0)
                body = self.rfile.read(ln) if ln else None
                backend = self._backend_for_path()
                self._respond(*self._forward(backend, body), backend)

            def do_GET(self):
                backend = self._backend_for_path()
                self._respond(*self._forward(backend, None), backend)

            def do_DELETE(self):
                backend = self._backend_for_path()
                self._respond(*self._forward(backend, None), backend)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_port
        self.uri = f"http://127.0.0.1:{self.port}"
        self._thread = threadreg.spawn(
            "proxy-server", self._httpd.serve_forever, owner="ProxyServer"
        )

    _MAX_TRACKED = 10_000

    def _remember(self, query_id: str, backend: str) -> None:
        with self._lock:
            self._owner[query_id] = backend
            # bounded: evict oldest entries past the cap (query ids of
            # drained queries are also dropped eagerly via _forget)
            while len(self._owner) > self._MAX_TRACKED:
                self._owner.pop(next(iter(self._owner)))

    def _forget(self, query_id: str) -> None:
        with self._lock:
            self._owner.pop(query_id, None)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
