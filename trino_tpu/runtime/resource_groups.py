"""Resource groups: hierarchical admission control.

Analogue of main/execution/resourcegroups/ (InternalResourceGroupManager,
InternalResourceGroup with hard/soft concurrency + queue limits,
selector-based routing — SURVEY.md §2.3) and the file-based config
plugin (trino-resource-group-managers). Groups form a tree; a query is
admitted when every group on its path has a free concurrency slot, else
it queues FIFO (the WeightedFairQueue reduces to FIFO until weights
land). Selectors map (user, source) -> group path."""

from __future__ import annotations

import dataclasses
import re
import threading
from typing import Dict, List, Optional, Tuple


class QueryQueueFullError(RuntimeError):
    pass


@dataclasses.dataclass
class ResourceGroupSpec:
    name: str
    max_concurrency: int = 10
    max_queued: int = 100
    sub_groups: List["ResourceGroupSpec"] = dataclasses.field(default_factory=list)


@dataclasses.dataclass(frozen=True)
class Selector:
    """Routes queries to a group path; regexes over user/source."""

    group: Tuple[str, ...]
    user_pattern: Optional[str] = None
    source_pattern: Optional[str] = None

    def matches(self, user: str, source: str) -> bool:
        if self.user_pattern and not re.fullmatch(self.user_pattern, user):
            return False
        if self.source_pattern and not re.fullmatch(self.source_pattern, source):
            return False
        return True


class _Group:
    def __init__(self, spec: ResourceGroupSpec, parent: Optional["_Group"]):
        self.spec = spec
        self.parent = parent
        self.running = 0
        self.queued = 0
        self.children: Dict[str, _Group] = {
            c.name: _Group(c, self) for c in spec.sub_groups
        }

    def path(self) -> str:
        parts = []
        g: Optional[_Group] = self
        while g is not None:
            parts.append(g.spec.name)
            g = g.parent
        return ".".join(reversed(parts))


class ResourceGroupManager:
    """Admission: acquire() blocks while the target group (or any
    ancestor) is at max_concurrency; raises QueryQueueFullError when the
    queue cap is hit (the dispatcher's resource-group submit path,
    DispatchManager.createQueryInternal:219)."""

    def __init__(self, root: ResourceGroupSpec, selectors: List[Selector] = ()):
        self._root = _Group(root, None)
        self._selectors = list(selectors)
        self._lock = threading.Condition()

    def _resolve(self, user: str, source: str) -> _Group:
        for s in self._selectors:
            if s.matches(user, source):
                g = self._root
                for name in s.group:
                    if name == self._root.spec.name:
                        continue
                    g = g.children[name]
                return g
        return self._root

    def _chain(self, g: _Group) -> List[_Group]:
        out = []
        while g is not None:
            out.append(g)
            g = g.parent
        return out

    def acquire(self, user: str = "user", source: str = "", timeout: float = 60.0):
        """Returns a lease token (the group) once admitted."""
        group = self._resolve(user, source)
        chain = self._chain(group)
        with self._lock:
            for g in chain:  # queue caps apply at EVERY level of the tree
                if g.queued >= g.spec.max_queued:
                    raise QueryQueueFullError(
                        f"group {g.path()} queue is full "
                        f"({g.spec.max_queued})"
                    )
            for g in chain:
                g.queued += 1
            try:
                ok = self._lock.wait_for(
                    lambda: all(
                        g.running < g.spec.max_concurrency for g in chain
                    ),
                    timeout=timeout,
                )
                if not ok:
                    raise QueryQueueFullError(
                        f"group {group.path()} admission timed out"
                    )
                for g in chain:
                    g.running += 1
            finally:
                for g in chain:
                    g.queued -= 1
        return group

    def release(self, group: _Group) -> None:
        with self._lock:
            for g in self._chain(group):
                g.running -= 1
            self._lock.notify_all()

    def stats(self) -> Dict[str, Tuple[int, int]]:
        """group path -> (running, queued)."""
        out: Dict[str, Tuple[int, int]] = {}

        def walk(g: _Group) -> None:
            out[g.path()] = (g.running, g.queued)
            for c in g.children.values():
                walk(c)

        with self._lock:
            walk(self._root)
        return out
