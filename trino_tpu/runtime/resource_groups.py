"""Resource groups: hierarchical admission control.

Analogue of main/execution/resourcegroups/ (InternalResourceGroupManager,
InternalResourceGroup with hard/soft concurrency + queue limits,
selector-based routing — SURVEY.md §2.3) and the file-based config
plugin (trino-resource-group-managers). Groups form a tree; a query is
admitted when every group on its path has a free concurrency slot.
Contending sibling groups share capacity by WEIGHTED FAIRNESS
(scheduling_weight, the WeightedFairQueue analogue realized as stride
scheduling: each admission advances the group's virtual pass by
1/weight and the smallest pass admits next; FIFO within a group). Selectors map (user, source) -> group path."""

from __future__ import annotations

import dataclasses
import re
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import Dict, List, Optional, Tuple


class QueryQueueFullError(RuntimeError):
    pass


class QueryKilledWhileQueuedError(RuntimeError):
    """The query was killed (DELETE / client abandon) while waiting for
    admission: its ticket is withdrawn without ever counting as running."""


@dataclasses.dataclass
class ResourceGroupSpec:
    name: str
    max_concurrency: int = 10
    max_queued: int = 100
    # relative share under a contended parent (WeightedFairQueue's
    # per-entry weight; execution/resourcegroups/WeightedFairQueue.java)
    scheduling_weight: int = 1
    sub_groups: List["ResourceGroupSpec"] = dataclasses.field(default_factory=list)


@dataclasses.dataclass(frozen=True)
class Selector:
    """Routes queries to a group path; regexes over user/source."""

    group: Tuple[str, ...]
    user_pattern: Optional[str] = None
    source_pattern: Optional[str] = None

    def matches(self, user: str, source: str) -> bool:
        if self.user_pattern and not re.fullmatch(self.user_pattern, user):
            return False
        if self.source_pattern and not re.fullmatch(self.source_pattern, source):
            return False
        return True


@dataclasses.dataclass
class _Ticket:
    """One waiting admission request (FIFO sequence within a group)."""

    seq: int
    admitted: bool = False


class _Group:
    def __init__(self, spec: ResourceGroupSpec, parent: Optional["_Group"]):
        self.spec = spec
        self.parent = parent
        self.running = 0
        self.queued = 0
        # stride-scheduling virtual pass: each admission advances the
        # group by 1/weight; the smallest pass admits next. New or
        # long-idle groups REJOIN at the scheduler's current pass, so
        # history never starves active siblings
        self.vpass = 0.0
        self.waiters: List["_Ticket"] = []
        self.children: Dict[str, _Group] = {
            c.name: _Group(c, self) for c in spec.sub_groups
        }

    def path(self) -> str:
        parts = []
        g: Optional[_Group] = self
        while g is not None:
            parts.append(g.spec.name)
            g = g.parent
        return ".".join(reversed(parts))


class ResourceGroupManager:
    """Admission: acquire() blocks while the target group (or any
    ancestor) is at max_concurrency; raises QueryQueueFullError when the
    queue cap is hit (the dispatcher's resource-group submit path,
    DispatchManager.createQueryInternal:219)."""

    def __init__(self, root: ResourceGroupSpec, selectors: List[Selector] = ()):
        self._root = _Group(root, None)
        self._selectors = list(selectors)
        self._lock = named_condition("ResourceGroupManager._lock")
        self._next_seq = 0
        self._gpass = 0.0

    def _resolve(self, user: str, source: str) -> _Group:
        for s in self._selectors:
            if s.matches(user, source):
                g = self._root
                for name in s.group:
                    if name == self._root.spec.name:
                        continue
                    g = g.children[name]
                return g
        return self._root

    def _chain(self, g: _Group) -> List[_Group]:
        out = []
        while g is not None:
            out.append(g)
            g = g.parent
        return out

    def _schedule_locked(self) -> None:
        """Admit as many waiting tickets as capacity allows, in
        weighted-fair order: among groups with waiters, the smallest
        stride-scheduling pass goes first (WeightedFairQueue's pick
        rule); FIFO within a group."""
        while True:
            candidates = []

            def collect(g: _Group) -> None:
                if g.waiters:
                    candidates.append(g)
                for c in g.children.values():
                    collect(c)

            collect(self._root)
            admitted = False
            for g in sorted(
                candidates,
                key=lambda g: (g.vpass, g.waiters[0].seq),
            ):
                chain = self._chain(g)
                if all(
                    x.running < x.spec.max_concurrency for x in chain
                ):
                    t = g.waiters.pop(0)
                    for x in chain:
                        x.running += 1
                        x.queued -= 1
                    # stride advance; global pass trails the winner so
                    # newcomers rejoin here, not at zero
                    self._gpass = max(self._gpass, g.vpass)
                    g.vpass = self._gpass + 1.0 / max(
                        g.spec.scheduling_weight, 1
                    )
                    t.admitted = True
                    admitted = True
                    break
            if not admitted:
                return

    def acquire(self, user: str = "user", source: str = "",
                timeout: float = 60.0, cancelled=None):
        """Returns a lease token (the group) once admitted. `cancelled`
        (optional zero-arg callable) is polled while waiting: when it
        turns true the ticket is withdrawn — releasing the queue slot
        without EVER counting toward `running` — and
        QueryKilledWhileQueuedError is raised (the dispatcher's
        killed-while-queued path)."""
        import time as _time

        group = self._resolve(user, source)
        chain = self._chain(group)
        deadline = _time.monotonic() + timeout
        with self._lock:
            t = _Ticket(self._next_seq)
            self._next_seq += 1
            for g in chain:
                g.queued += 1
            if not group.waiters:
                # rejoin at the current pass: idle history is not a
                # credit (the starvation guard of stride scheduling)
                group.vpass = max(group.vpass, self._gpass)
            group.waiters.append(t)
            self._schedule_locked()
            if not t.admitted:
                # the queue cap counts WAITING queries only — a query
                # admitted on arrival never queued (every tree level
                # applies its own cap)
                for g in chain:
                    if g.queued > g.spec.max_queued:
                        group.waiters.remove(t)
                        for x in chain:
                            x.queued -= 1
                        raise QueryQueueFullError(
                            f"group {g.path()} queue is full "
                            f"({g.spec.max_queued})"
                        )
            self._lock.notify_all()
            was_cancelled = False
            try:
                while not t.admitted:
                    if cancelled is not None and cancelled():
                        was_cancelled = True
                        break
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        break
                    # chunked wait so a kill is noticed promptly even
                    # with a long admission timeout
                    self._lock.wait_for(
                        lambda: t.admitted,
                        timeout=remaining if cancelled is None
                        else min(remaining, 0.05),
                    )
            finally:
                if not t.admitted:
                    # timed out, killed, or interrupted: withdraw the
                    # ticket (queue slot released, `running` untouched)
                    if t in group.waiters:
                        group.waiters.remove(t)
                    for g in chain:
                        g.queued -= 1
            if t.admitted and cancelled is not None and cancelled():
                # killed in the admit-to-wakeup window: hand the slot
                # straight back so it cannot leak
                for g in chain:
                    g.running -= 1
                self._schedule_locked()
                self._lock.notify_all()
                was_cancelled = True
            if was_cancelled:
                raise QueryKilledWhileQueuedError(
                    f"query killed while queued in group {group.path()}"
                )
            if not t.admitted:
                raise QueryQueueFullError(
                    f"group {group.path()} admission timed out"
                )
        return group

    def release(self, group: _Group) -> None:
        with self._lock:
            for g in self._chain(group):
                g.running -= 1
            self._schedule_locked()
            self._lock.notify_all()

    def total_running(self) -> int:
        """Admitted-and-not-yet-released queries across the whole tree
        (the root's counter — every admission increments it). The
        abandonment reaper's post-condition: after a reaped query
        unwinds, this must drop back, or a slot leaked."""
        with self._lock:
            return self._root.running

    def stats(self) -> Dict[str, Tuple[int, int]]:
        """group path -> (running, queued)."""
        out: Dict[str, Tuple[int, int]] = {}

        def walk(g: _Group) -> None:
            out[g.path()] = (g.running, g.queued)
            for c in g.children.values():
                walk(c)

        with self._lock:
            walk(self._root)
        return out
