"""Typed wire codec for task specs and plan fragments.

Analogue of the reference's Jackson JSON codecs for TaskUpdateRequest /
PlanFragment (main/server/remotetask/HttpRemoteTask.java posts a
JSON-codec'd TaskUpdateRequest; io.trino.sql.planner.PlanFragment is a
@JsonCreator type). The engine's plan IR is frozen dataclasses, so the
codec is a tagged, ALLOWLISTED dataclass walker:

- encode() lowers a TaskSpec (or any registered dataclass tree) to
  JSON-compatible dicts: {"$": ClassName, "f": {field: value}} with
  explicit tags for tuples, dicts with non-string keys, enums, bytes.
- decode() rebuilds the tree, refusing any class not in the registry —
  this is what makes the worker's task endpoint safe: unlike pickle,
  a request body can only ever instantiate the types listed here
  (spec posts used to be `pickle.loads` on an HTTP port: remote code
  execution for anyone who could reach an unauthenticated worker).

Callables (in-process fetch closures) are NOT encodable by design;
cross-process specs carry descriptor tuples (see task._resolve_fetch),
and attempting to encode a closure raises CodecError loudly.
"""

from __future__ import annotations

import base64
import dataclasses
import enum
import json
from typing import Any, Dict

import numpy as np

from trino_tpu import types as T
from trino_tpu.block import Dictionary
from trino_tpu.connectors.spi import ColumnMetadata, Split, TableHandle
from trino_tpu.expr import ir
from trino_tpu.ops.sort import SortKey
from trino_tpu.sql import plan as P
from trino_tpu.sql.fragmenter import PlanFragment


class CodecError(ValueError):
    pass


def _registry() -> Dict[str, type]:
    import trino_tpu.runtime.task as task_mod

    classes = [
        # plan IR
        P.Field, P.ScanNode, P.ValuesNode, P.FilterNode, P.ProjectNode,
        P.AggCall, P.AggregateNode, P.JoinNode, P.WindowFuncSpec,
        P.WindowNode, P.UnnestNode, P.MeasureSpec, P.MatchRecognizeNode,
        P.SortNode, P.TopNNode, P.LimitNode, P.EnforceSingleRowNode,
        P.UnionAllNode, P.OutputNode, P.ExchangeNode, P.RemoteSourceNode,
        # expression IR
        ir.InputRef, ir.Literal, ir.Call, ir.Cast, ir.Case, ir.InList,
        # support types
        T.DataType, SortKey, TableHandle, Split, ColumnMetadata,
        PlanFragment,
        # task layer
        task_mod.TaskId, task_mod.TaskSpec,
    ]
    # adaptive tier: spooled subtrees (and the exact observed stats
    # they carry) travel inside distributed fragments
    from trino_tpu.adaptive.spool import SpooledValuesNode
    from trino_tpu.sql.stats import ColStats, PlanStats

    classes += [SpooledValuesNode, PlanStats, ColStats]
    return {c.__name__: c for c in classes}


_REGISTRY: Dict[str, type] = {}


def registry() -> Dict[str, type]:
    global _REGISTRY
    if not _REGISTRY:
        _REGISTRY = _registry()
    return _REGISTRY


def encode(obj: Any) -> Any:
    """Lower to JSON-compatible structures (dicts/lists/scalars)."""
    if obj is None or isinstance(obj, (bool, str)):
        return obj
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, int):
        return obj
    if isinstance(obj, float):
        return obj
    if isinstance(obj, bytes):
        return {"$": "~bytes", "v": base64.b64encode(obj).decode("ascii")}
    if isinstance(obj, enum.Enum):
        # TypeKind and friends: encoded by name, decoded via the class
        return {"$": "~enum", "c": type(obj).__name__, "v": obj.name}
    if isinstance(obj, tuple):
        return {"$": "~tuple", "v": [encode(v) for v in obj]}
    if isinstance(obj, list):
        return {"$": "~list", "v": [encode(v) for v in obj]}
    if isinstance(obj, dict):
        return {
            "$": "~dict",
            "v": [[encode(k), encode(v)] for k, v in obj.items()],
        }
    if isinstance(obj, Dictionary):
        return {"$": "~strdict", "v": list(obj.values)}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        name = type(obj).__name__
        if name not in registry():
            raise CodecError(f"unregistered dataclass {name!r}")
        return {
            "$": name,
            "f": {
                f.name: encode(getattr(obj, f.name))
                for f in dataclasses.fields(obj)
            },
        }
    raise CodecError(f"unencodable value of type {type(obj).__name__!r}")


_ENUMS = {"TypeKind": T.TypeKind}


def decode(obj: Any) -> Any:
    """Inverse of encode(). Unknown tags raise CodecError."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, list):
        return [decode(v) for v in obj]
    if isinstance(obj, dict):
        tag = obj.get("$")
        if tag is None:
            raise CodecError("untagged object in wire payload")
        if tag == "~bytes":
            return base64.b64decode(obj["v"])
        if tag == "~tuple":
            return tuple(decode(v) for v in obj["v"])
        if tag == "~list":
            return [decode(v) for v in obj["v"]]
        if tag == "~dict":
            return {decode(k): decode(v) for k, v in obj["v"]}
        if tag == "~strdict":
            return Dictionary(obj["v"])
        if tag == "~enum":
            cls = _ENUMS.get(obj["c"])
            if cls is None:
                raise CodecError(f"unknown enum {obj['c']!r}")
            return cls[obj["v"]]
        cls = registry().get(tag)
        if cls is None:
            raise CodecError(f"unknown wire class {tag!r}")
        fields = {f.name for f in dataclasses.fields(cls)}
        kwargs = {}
        for k, v in obj.get("f", {}).items():
            if k not in fields:
                raise CodecError(f"{tag}: unknown field {k!r}")
            kwargs[k] = decode(v)
        return cls(**kwargs)
    raise CodecError(f"undecodable wire value of type {type(obj).__name__!r}")


def dumps(obj: Any) -> bytes:
    return json.dumps(encode(obj), separators=(",", ":")).encode("utf-8")


def loads(data: bytes) -> Any:
    return decode(json.loads(data.decode("utf-8")))
