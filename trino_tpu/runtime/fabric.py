"""Multi-host replica fabric: checkpoint transport + membership bridge.

PR 17 made mesh checkpoints host-portable (`MeshCheckpointStore.
export_bytes` / `import_bytes`, generation fencing, the device-identity-
free checkpoint key) but left the wire out: every byte stayed inside one
coordinator process, so a real host loss stranded its in-flight queries
with no sibling able to fetch the last snapshot. This module is that
wire, plus the membership tier that decides who the siblings ARE:

- **checkpoint transport** — `CheckpointPusher` ships `export_bytes`
  payloads to peer coordinators over the HTTP layer (runtime/http.py
  FabricServer/FabricClient), each call wrapped in the PR 2
  RequestErrorTracker backoff/budget loop, with a sha256 content digest
  verified before the receiver's generation-fenced `import_bytes`.
  Pushes ride a bounded queue drained by a daemon thread: the chunk
  loop only ever enqueues, and a full queue SHEDS the push
  (fabric.push_sheds) rather than blocking a chunk boundary. Pulls run
  on demand at failover (`Fabric.try_pull`).
- **membership** — `MembershipDriver` subscribes to the NodeManager
  heartbeat tier (discovery.py state listeners) and drives
  `ReplicaManager.leave` / `.join` under the monotonic membership
  epoch: placement and failover consult live membership, breaker state
  survives flaps (the Replica object persists), and a resume targeting
  a replica whose epoch moved is refused with the typed
  `MembershipEpochError` — then restarted fresh — instead of carrying
  stale state onto what is effectively a new host.
- **warm join** — a joining host replays the peer's warm-class
  manifest (compile/warmup.py `warm_manifest`/`apply_manifest`) and
  the census-driven mesh WarmupEntry registry BEFORE it enters the
  placement pool, so its first placed query mints zero new lowerings.

Counters surface through /v1/metrics under the `fabric.` prefix and
through the EXPLAIN ANALYZE `membership=` line (replicas.py
`membership_line`).
"""

from __future__ import annotations

import base64
import hashlib
import pickle
import queue
import threading
from trino_tpu.analysis import threadreg
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import Callable, List, Optional, Tuple

# /v1/metrics counter names (registered at zero by
# register_fabric_metrics — same surface protocol as the recovery,
# replica and scheduler counters)
PUSHES = "fabric.pushes"
PULLS = "fabric.pulls"
PUSH_SHEDS = "fabric.push_sheds"
DIGEST_REJECTS = "fabric.digest_rejects"
JOINS = "fabric.joins"
LEAVES = "fabric.leaves"
EPOCH_FENCES = "fabric.epoch_fences"

_COUNTERS = (
    PUSHES, PULLS, PUSH_SHEDS, DIGEST_REJECTS, JOINS, LEAVES, EPOCH_FENCES,
)


def register_fabric_metrics() -> None:
    from trino_tpu.runtime.metrics import METRICS

    for name in _COUNTERS:
        METRICS.increment(name, 0.0)


class MembershipEpochError(RuntimeError):
    """A resume targeted a replica whose membership epoch moved past
    the epoch its checkpoint context was taken under (the replica left
    and rejoined in between). Typed so the dispatcher can discard the
    stale context and restart fresh instead of carrying old state onto
    what is effectively a new host."""

    def __init__(self, message: str, replica_id: Optional[int] = None,
                 expected_epoch: Optional[int] = None,
                 actual_epoch: Optional[int] = None):
        super().__init__(message)
        self.replica_id = replica_id
        self.expected_epoch = expected_epoch
        self.actual_epoch = actual_epoch


# -- wire helpers -----------------------------------------------------


def checkpoint_digest(data: bytes) -> str:
    """Content digest of a serialized checkpoint: transport corruption
    (truncation, bit flips) is rejected BEFORE import_bytes ever sees
    the payload, so a corrupt transfer degrades to a clean restart
    rather than a poisoned store."""
    return hashlib.sha256(data).hexdigest()


def encode_key(key: tuple) -> str:
    """URL-safe transport form of a checkpoint key (the device-
    identity-free program tuple). Pickled like the checkpoint payload
    itself — both travel only inside the internal-auth trust domain
    (FabricServer refuses to start networked without a secret)."""
    raw = pickle.dumps(key, protocol=pickle.HIGHEST_PROTOCOL)
    return base64.urlsafe_b64encode(raw).decode("ascii")


def decode_key(ekey: str) -> tuple:
    key = pickle.loads(base64.urlsafe_b64decode(ekey.encode("ascii")))
    if not isinstance(key, tuple):
        raise TypeError(f"fabric key decoded to {type(key).__name__}")
    return key


# -- endpoint logic (behind runtime/http.py FabricServer) -------------


class HostFabric:
    """One host's fabric endpoint state: the receive/serve logic behind
    the FabricServer routes, bound to this process's checkpoint
    store."""

    def __init__(self, store=None, host_id: str = ""):
        if store is None:
            from trino_tpu.recovery.checkpoint import CHECKPOINTS

            store = CHECKPOINTS
        self.store = store
        self.host_id = host_id
        self.received = 0
        self.served = 0
        self.digest_rejects = 0
        register_fabric_metrics()

    def receive_checkpoint(self, ekey: str, data: bytes,
                           digest: str) -> dict:
        """POST /v1/fabric/checkpoint/{ekey}: verify the content digest,
        then land the bytes under the LOCAL generation check
        (import_bytes). Either rejection — digest mismatch or
        undecodable payload — leaves the store untouched; the pusher
        side treats the outcome as advisory (push is best-effort)."""
        from trino_tpu.runtime.metrics import METRICS

        if checkpoint_digest(data) != digest:
            self.digest_rejects += 1
            METRICS.increment(DIGEST_REJECTS)
            return {"imported": False, "reason": "digest_mismatch"}
        try:
            key = decode_key(ekey)
        except Exception:
            self.digest_rejects += 1
            METRICS.increment(DIGEST_REJECTS)
            return {"imported": False, "reason": "bad_key"}
        # rebase_epoch: the sender's global generation epoch is
        # process-local noise across hosts; per-table write counters
        # keep DML fencing live (checkpoint.py import_bytes)
        ok = self.store.import_bytes(key, data, rebase_epoch=True)
        if ok:
            self.received += 1
        return {"imported": bool(ok)}

    def serve_checkpoint(self, ekey: str) -> Optional[Tuple[bytes, str]]:
        """GET /v1/fabric/checkpoint/{ekey}: export the live entry (via
        `get`, so stale generations are never served) with its digest.
        None -> 404."""
        key = decode_key(ekey)
        data = self.store.export_bytes(key)
        if data is None:
            return None
        self.served += 1
        return data, checkpoint_digest(data)

    def status(self) -> dict:
        return {
            "host_id": self.host_id,
            "entries": len(self.store),
            "received": self.received,
            "served": self.served,
            "digest_rejects": self.digest_rejects,
        }


# -- push side --------------------------------------------------------


class CheckpointPusher:
    """Bounded asynchronous push queue over a set of peer clients.

    The chunk loop's checkpoint hook calls `offer(key)` — non-blocking
    by construction: a full queue sheds the push (the NEXT boundary's
    snapshot supersedes this one anyway) and the worker thread does the
    export + HTTP on its own time, inside each client's
    RequestErrorTracker budget. A push failure after the budget is
    spent is dropped: the fabric degrades to pull-on-demand (or a cold
    restart), never to a blocked or failed query."""

    _STOP = object()

    def __init__(self, store, clients: List, depth: int = 8):
        self.store = store
        self.clients = list(clients)
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._busy = 0  # guarded_by: _lock
        self._lock = named_lock("CheckpointPusher._lock")
        self.pushes = 0
        self.sheds = 0
        self.push_failures = 0
        self._thread = threadreg.spawn(
            "trino-tpu-fabric-push", self._run, owner="CheckpointPusher"
        )

    def offer(self, key: tuple) -> bool:
        try:
            self._q.put_nowait(key)
            return True
        except queue.Full:
            from trino_tpu.runtime.metrics import METRICS

            self.sheds += 1
            METRICS.increment(PUSH_SHEDS)
            return False

    def queued(self) -> int:
        with self._lock:
            return self._q.qsize() + self._busy

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Wait for every enqueued push to complete (tests and the
        multihost smoke's pre-kill flush). True when drained."""
        import time

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.queued() == 0:
                return True
            import time as _t

            _t.sleep(0.005)
        return self.queued() == 0

    def stop(self) -> None:
        self._q.put(self._STOP)
        self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while True:
            key = self._q.get()
            if key is self._STOP:
                return
            with self._lock:
                self._busy += 1
            try:
                self._push(key)
            finally:
                with self._lock:
                    self._busy -= 1

    def _push(self, key: tuple) -> None:
        from trino_tpu.runtime.metrics import METRICS

        data = self.store.export_bytes(key)
        if data is None:
            return  # completed/invalidated since the boundary: nothing to ship
        digest = checkpoint_digest(data)
        for client in self.clients:
            try:
                client.push_checkpoint(key, data, digest=digest)
                self.pushes += 1
                METRICS.increment(PUSHES)
            except Exception:
                # budget spent (RequestFailedError) or protocol error:
                # drop the push — the receiver can still pull on demand
                self.push_failures += 1


# -- process attachment -----------------------------------------------


class Fabric:
    """One coordinator process's fabric attachment: the push queue over
    its peer set plus pull-on-demand for failover."""

    def __init__(self, peer_uris: List[str], store=None,
                 internal_secret: Optional[str] = "__env__",
                 queue_depth: int = 8,
                 max_error_duration_s: float = 5.0):
        from trino_tpu.runtime.error_tracker import RetryPolicy
        from trino_tpu.runtime.http import FabricClient

        if store is None:
            from trino_tpu.recovery.checkpoint import CHECKPOINTS

            store = CHECKPOINTS
        self.store = store
        self.peer_uris = list(peer_uris)
        policy = RetryPolicy(
            max_error_duration_s=float(max_error_duration_s),
            min_backoff_s=0.01, max_backoff_s=0.5,
        )
        self.clients = [
            FabricClient(
                uri, internal_secret=internal_secret, retry_policy=policy,
            )
            for uri in self.peer_uris
        ]
        self.pusher = CheckpointPusher(store, self.clients, depth=queue_depth)
        register_fabric_metrics()

    def push_hook(self) -> Callable[[tuple], None]:
        """The mesh chunk loop's CHECKPOINT_PUSH_HOOK: enqueue-only."""
        def hook(key: tuple) -> None:
            self.pusher.offer(key)

        return hook

    def try_pull(self, key: tuple) -> bool:
        """Failover pull: ask each peer for the key, verify the digest,
        and land the first good payload under the local generation
        check. False when no peer has it (or every transfer failed its
        budget) — the caller restarts cold."""
        from trino_tpu.runtime.metrics import METRICS

        for client in self.clients:
            try:
                data, digest = client.pull_checkpoint(key)
            except Exception:
                continue  # budget spent on this peer: try the next
            if data is None:
                continue
            if digest and checkpoint_digest(data) != digest:
                METRICS.increment(DIGEST_REJECTS)
                continue
            if self.store.import_bytes(key, data, rebase_epoch=True):
                METRICS.increment(PULLS)
                return True
        return False

    def stop(self) -> None:
        self.pusher.stop()


# the process's active attachment (one coordinator, one fabric — set by
# maybe_start_fabric, mirrors recovery.CHECKPOINTS)
_fabric_lock = named_lock("fabric._fabric_lock")
ACTIVE_FABRIC: Optional[Fabric] = None  # guarded_by: _fabric_lock


def active_fabric() -> Optional[Fabric]:
    return ACTIVE_FABRIC  # unguarded-ok: atomic reference read


def maybe_start_fabric(session, store=None) -> Optional[Fabric]:
    """Attach the fabric when `session.fabric_peers` names peers (and
    re-attach when the peer set changed): builds the push queue and
    installs the chunk loop's checkpoint push hook. A session without
    peers leaves any existing attachment alone — SET SESSION on one
    query must not tear down another's transport."""
    global ACTIVE_FABRIC
    peers = [
        p.strip()
        for p in str(getattr(session, "fabric_peers", "") or "").split(",")
        if p.strip()
    ]
    if not peers:
        return ACTIVE_FABRIC  # unguarded-ok: atomic reference read
    with _fabric_lock:
        if ACTIVE_FABRIC is not None and ACTIVE_FABRIC.peer_uris == peers:
            return ACTIVE_FABRIC
        if ACTIVE_FABRIC is not None:
            ACTIVE_FABRIC.stop()
        fab = Fabric(
            peers, store=store,
            queue_depth=int(
                getattr(session, "fabric_queue_depth", 8) or 8
            ),
            max_error_duration_s=float(
                getattr(session, "fabric_max_error_duration_s", 5.0) or 5.0
            ),
        )
        from trino_tpu.parallel import mesh_chunk

        mesh_chunk.CHECKPOINT_PUSH_HOOK = fab.push_hook()
        ACTIVE_FABRIC = fab
        return fab


def stop_fabric() -> None:
    """Detach and stop the active fabric (tests, process shutdown)."""
    global ACTIVE_FABRIC
    with _fabric_lock:
        if ACTIVE_FABRIC is None:
            return
        from trino_tpu.parallel import mesh_chunk

        mesh_chunk.CHECKPOINT_PUSH_HOOK = None
        ACTIVE_FABRIC.stop()
        ACTIVE_FABRIC = None


def fabric_status() -> dict:
    """The /v1/fabric surface: counter snapshot + attachment state."""
    from trino_tpu.runtime.metrics import METRICS

    s = METRICS.snapshot()
    out = {
        name.split(".", 1)[1]: int(s.get(name, 0.0)) for name in _COUNTERS
    }
    fab = ACTIVE_FABRIC  # unguarded-ok: atomic reference read
    out["attached"] = fab is not None
    if fab is not None:
        out["peers"] = list(fab.peer_uris)
        out["queued"] = fab.pusher.queued()
        out["push_failures"] = fab.pusher.push_failures
    return out


# -- warm join --------------------------------------------------------


def warm_join_manifest() -> dict:
    """What a serving host hands a joining peer: the warm-class census
    (compile/warmup.py) plus the program-cache key fingerprints —
    everything the joiner needs to pre-compile before placement."""
    from trino_tpu.compile.cache import PROGRAM_CACHE
    from trino_tpu.compile.warmup import warm_manifest

    return {
        "classes": warm_manifest(),
        "programs": PROGRAM_CACHE.fingerprints(),
    }


def warm_join_replay(manifest: Optional[dict] = None,
                     mode: str = "block",
                     timeout_s: float = 60.0) -> int:
    """Warm a joining host/replica BEFORE it enters the placement pool:
    register the peer manifest's warm classes, then replay the local
    census-driven mesh WarmupEntry registry so the joiner's first
    placed query dispatches into populated jit caches — zero new
    lowerings. Returns the number of manifest classes applied. Never
    raises: warmup can delay a join, not fail it."""
    from trino_tpu.compile.warmup import WarmupService, apply_manifest
    from trino_tpu.parallel.mesh_chunk import mesh_warmup_entries

    applied = 0
    try:
        if manifest:
            applied = apply_manifest(manifest.get("classes", []))
        entries = mesh_warmup_entries()
        if entries:
            WarmupService(entries, mode=mode).start().wait(timeout_s)
    except Exception:
        pass
    return applied


# -- membership bridge ------------------------------------------------


class MembershipDriver:
    """Bridges the NodeManager heartbeat tier to replica membership:
    node state transitions (discovery.py add_state_listener) drive
    ReplicaManager.leave/join under the monotonic membership epoch.
    `replica_of` maps a worker_id to the replica it backs (None =
    not a replica host); `warm` is the joining-host warmup replay run
    before a rejoin enters the placement pool."""

    def __init__(self, node_manager, replica_manager,
                 replica_of: Optional[Callable[[str], Optional[int]]] = None,
                 warm: Optional[Callable[[], object]] = None):
        self.node_manager = node_manager
        self.replica_manager = replica_manager
        self.replica_of = replica_of or (lambda worker_id: None)
        self.warm = warm if warm is not None else warm_join_replay
        node_manager.add_state_listener(self._on_state)

    def _on_state(self, worker_id: str, old: str, new: str) -> None:
        rid = self.replica_of(worker_id)
        if rid is None:
            return
        if new in ("failed", "shutting_down", "drained") and old == "active":
            self.replica_manager.leave(rid)
        elif new == "active" and old != "active":
            self.replica_manager.join(rid, warm=self.warm)
