"""Coordinator HTTP server: the client statement protocol.

Analogue of the reference's client protocol (client/trino-client
StatementClientV1.java:65 — POST /v1/statement, poll nextUri, token-
paged results; QueuedStatementResource.java:106 +
ExecutingStatementResource.java:73 — SURVEY.md §2.11, §3.1). Queries
run asynchronously on an executor; clients poll:

  POST /v1/statement               SQL text -> {id, nextUri, stats}
  GET  /v1/statement/executing/{id}/{token}
                                   {columns, data, nextUri?, stats}
  DELETE /v1/statement/executing/{id}     cancel

Data pages out in row chunks per poll (the JSON protocol's data field).
"""

from __future__ import annotations

import json
import threading
from trino_tpu.analysis import threadreg
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

ROWS_PER_PAGE = 4096

# Minimal coordinator dashboard (the reference ships a React SPA under
# main/server/ui/ + webapp assets; this is the same information surface
# — cluster stats + query list — as one self-contained page).
_UI_HTML = """<!doctype html>
<html><head><title>trino-tpu</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2rem; }
 h1 { font-size: 1.3rem; } .stats span { margin-right: 2rem; }
 table { border-collapse: collapse; margin-top: 1rem; width: 100%; }
 td, th { border: 1px solid #ccc; padding: 4px 8px; font-size: 0.85rem;
          text-align: left; }
 .finished { color: #2a7d2a; } .failed { color: #b22; }
 .running, .queued { color: #b80; }
</style></head>
<body>
<h1>trino-tpu coordinator</h1>
<div class="stats" id="stats">loading…</div>
<table><thead><tr><th>query id</th><th>state</th><th>rows</th>
<th>sql</th></tr></thead><tbody id="queries"></tbody></table>
<script>
async function tick() {
  try {
    const s = await (await fetch('/v1/cluster')).json();
    document.getElementById('stats').innerHTML =
      `<span>queries: ${s.total_queries}</span>` +
      `<span>running: ${s.running_queries}</span>` +
      `<span>finished: ${s.finished_queries}</span>` +
      `<span>failed: ${s.failed_queries}</span>`;
    const q = await (await fetch('/v1/query')).json();
    document.getElementById('queries').innerHTML = q.map(j =>
      `<tr><td>${j.id}</td><td class="${j.state}">${j.state}</td>` +
      `<td>${j.rows}</td><td><code>${j.sql.replace(/</g,'&lt;')}</code></td></tr>`
    ).join('');
  } catch (e) { /* server gone */ }
}
tick(); setInterval(tick, 2000);
</script></body></html>
"""


class _QueryJob:
    def __init__(self, query_id: str, sql: str, user: Optional[str] = None):
        self.query_id = query_id
        self.sql = sql
        self.user = user
        self.state = "queued"
        self.rows: List[list] = []
        self.columns: List[dict] = []
        self.error: Optional[str] = None
        self.started_transaction_id: Optional[str] = None
        self.added_prepare = None
        self.deallocated_prepare = None
        self.cleared_transaction = False
        self.finished_at: Optional[float] = None  # monotonic, for TTL expiry
        self.drained = False  # final result page delivered to the client
        self.abandoned = False
        self.created_at = time.monotonic()  # admission-queue wait base
        self.last_heartbeat = time.monotonic()  # any client poll refreshes
        self.lock = named_lock("_QueryJob.lock")

    def snapshot(self, token: int):
        with self.lock:
            self.last_heartbeat = time.monotonic()
            return (
                self.state,
                self.columns,
                self.rows[token : token + ROWS_PER_PAGE],
                len(self.rows),
                self.error,
            )


class CoordinatorServer:
    """HTTP front for any runner with .execute(sql) -> MaterializedResult
    (LocalQueryRunner or DistributedQueryRunner)."""

    def __init__(
        self,
        runner,
        port: int = 0,
        max_concurrent: int = 4,
        resource_groups=None,  # runtime.resource_groups.ResourceGroupManager
        authenticator=None,  # security.Authenticator; None = insecure
        client_timeout_s: Optional[float] = None,
        reap_interval_s: Optional[float] = None,
        admission=None,  # serving.admission.AdmissionPipeline
        batcher=None,  # serving.batcher.MicroBatcher
    ):
        from trino_tpu.security import AuthenticationError, InsecureAuthenticator

        self.runner = runner
        self.resource_groups = resource_groups
        self.authenticator = authenticator or InsecureAuthenticator()
        # serving tier: lane-based admission (shed with 429 instead of
        # queueing without bound) and optional point-lookup coalescing
        _sess = getattr(runner, "session", None)
        if admission is None:
            from trino_tpu.serving.admission import AdmissionPipeline

            admission = AdmissionPipeline(
                resource_groups,
                fast_depth=int(
                    getattr(_sess, "admission_fast_depth", 64) or 64
                ),
                general_depth=int(
                    getattr(_sess, "admission_general_depth", 256) or 256
                ),
                retry_after_s=float(
                    getattr(_sess, "admission_retry_after_s", 1.0) or 1.0
                ),
            )
        self.admission = admission
        # replica-plane visibility in admission stats (the manager is
        # carved lazily by the runner, hence a supplier, not a value)
        self.admission.attach_replicas(
            lambda: getattr(runner, "_replicas", None)
        )
        _window_ms = float(
            getattr(_sess, "micro_batch_window_ms", 0.0) or 0.0
        )
        if batcher is None and _window_ms > 0:
            from trino_tpu.serving.batcher import MicroBatcher

            batcher = MicroBatcher(
                runner,
                window_s=_window_ms / 1000.0,
                max_batch=int(getattr(_sess, "micro_batch_max", 16) or 16),
            )
        self.batcher = batcher
        self._jobs: Dict[str, _QueryJob] = {}
        self._pool = ThreadPoolExecutor(max_workers=max_concurrent)
        # client-abandonment TTL: explicit arg wins, else the runner
        # session's client_timeout_s, else the class default
        if client_timeout_s is None:
            client_timeout_s = getattr(
                getattr(runner, "session", None), "client_timeout_s", None
            )
        if client_timeout_s:
            self.CLIENT_TTL_S = float(client_timeout_s)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code: int, obj, headers=None) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def _auth(self):
                """Authenticate or answer 401 (the reference's
                authenticator filter chain, main/server/security/)."""
                try:
                    return outer.authenticator.authenticate(self.headers)
                except AuthenticationError as ex:
                    # drain the request body first: HTTP/1.1 keep-alive
                    # would otherwise parse the unread body bytes as
                    # the connection's next request line
                    ln = int(self.headers.get("Content-Length", "0") or 0)
                    if ln:
                        self.rfile.read(ln)
                    body = json.dumps({"error": f"Unauthorized: {ex}"}).encode()
                    self.send_response(401)
                    self.send_header("WWW-Authenticate", "Basic, Bearer")
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return None

            def do_POST(self):
                identity = self._auth()
                if identity is None:
                    return
                parts = [p for p in self.path.split("/") if p]
                if parts == ["v1", "statement"]:
                    ln = int(self.headers.get("Content-Length", "0"))
                    sql = self.rfile.read(ln).decode("utf-8")
                    # per-connection transaction threading: the client
                    # carries its transaction id on every request
                    # (StatementClientV1's X-Trino-Transaction-Id)
                    txn = self.headers.get("X-Trino-Transaction-Id", "NONE")
                    # prepared statements are CLIENT session state,
                    # carried per request (X-Trino-Prepared-Statement:
                    # name=urlencoded-sql, repeatable)
                    import urllib.parse as _up

                    prepared = {}
                    for hv in self.headers.get_all(
                        "X-Trino-Prepared-Statement"
                    ) or []:
                        for part in hv.split(","):
                            if "=" in part:
                                k, v = part.split("=", 1)
                                prepared[k.strip()] = _up.unquote(v)
                    from trino_tpu.serving.admission import (
                        OverloadSheddedError,
                    )

                    try:
                        job = outer._submit(sql, identity, txn, prepared)
                    except OverloadSheddedError as ex:
                        # shed at admission: the client backs off and
                        # retries instead of growing an unbounded queue
                        self._json(
                            429,
                            {"error": {
                                "message": str(ex),
                                "errorName": "SERVER_OVERLOADED",
                            }},
                            headers={"Retry-After": f"{ex.retry_after_s:g}"},
                        )
                        return
                    self._json(200, outer._response(job, 0))
                    return
                self._json(404, {"error": "no route"})

            def do_GET(self):
                identity = self._auth()
                if identity is None:
                    return
                parts = [p for p in self.path.split("/") if p]
                if (
                    len(parts) == 5
                    and parts[:3] == ["v1", "statement", "executing"]
                ):
                    job = outer._jobs.get(parts[3])
                    if job is None:
                        self._json(404, {"error": "unknown query"})
                        return
                    self._json(200, outer._response(job, int(parts[4])))
                    return
                # observability REST surface (QueryResource /
                # ClusterStatsResource analogues) + the web UI page
                if parts == ["v1", "cluster"]:
                    self._json(200, outer.cluster_stats())
                    return
                if parts == ["v1", "metrics"]:
                    from trino_tpu.runtime.metrics import METRICS

                    self._json(200, METRICS.snapshot())
                    return
                if parts == ["v1", "fabric"]:
                    from trino_tpu.runtime.fabric import fabric_status

                    self._json(200, fabric_status())
                    return
                if parts == ["v1", "query"]:
                    self._json(200, outer.query_list(identity))
                    return
                # per-query observability: aggregated QueryInfo and the
                # Perfetto-loadable span tree (distributed runner only —
                # getattr guards the local runner, which lacks the
                # completed-query registry)
                if len(parts) == 3 and parts[:2] == ["v1", "query"]:
                    fn = getattr(outer.runner, "query_info", None)
                    info = fn(parts[2]) if fn is not None else None
                    if info is None:
                        self._json(404, {"error": "unknown query"})
                    else:
                        self._json(200, info)
                    return
                if (
                    len(parts) == 4
                    and parts[:2] == ["v1", "query"]
                    and parts[3] == "trace"
                ):
                    fn = getattr(outer.runner, "query_chrome_trace", None)
                    tr = fn(parts[2]) if fn is not None else None
                    if tr is None:
                        self._json(404, {"error": "no trace for query"})
                    else:
                        self._json(200, tr)
                    return
                if len(parts) == 2 and parts[0] == "v1" and parts[1] == "info":
                    self._json(200, {"starting": False, "uptime": "n/a"})
                    return
                if parts == ["ui"] or parts == []:
                    body = _UI_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self._json(404, {"error": "no route"})

            def do_DELETE(self):
                if self._auth() is None:
                    return
                parts = [p for p in self.path.split("/") if p]
                if (
                    len(parts) == 4
                    and parts[:3] == ["v1", "statement", "executing"]
                ):
                    outer._kill(parts[3])
                    self._json(200, {})
                    return
                self._json(404, {"error": "no route"})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_port
        self.uri = f"http://127.0.0.1:{self.port}"
        self._thread = threadreg.spawn(
            "statement-server", self._httpd.serve_forever, owner="StatementServer"
        )
        # abandonment reaper: _evict_completed used to run only on
        # submit, so an idle server never noticed a vanished client —
        # the RUNNING query it left behind kept its resource-group slot
        # and memory forever. The reaper ticks independently of traffic;
        # the running query observes job.abandoned through the `cancel`
        # hook passed to runner.execute and unwinds, releasing both.
        self._reaper_stop = threading.Event()
        self._reap_interval_s = (
            reap_interval_s
            if reap_interval_s is not None
            else max(0.05, min(1.0, self.CLIENT_TTL_S / 4.0))
        )

        def _reap_loop():
            while not self._reaper_stop.wait(self._reap_interval_s):
                try:
                    self._evict_completed()
                except Exception:
                    pass  # a reaper crash must not take the server down

        self._reaper = threadreg.spawn(
            "client-reaper", _reap_loop, owner="StatementServer"
        )

    def cluster_stats(self) -> dict:
        """ClusterStatsResource analogue."""
        states = [j.state for j in list(self._jobs.values())]
        return {
            "total_queries": len(states),
            "running_queries": sum(1 for s in states if s in ("queued", "running")),
            "finished_queries": sum(1 for s in states if s == "finished"),
            "failed_queries": sum(1 for s in states if s == "failed"),
        }

    def query_list(self, identity=None) -> list:
        """QueryResource GET /v1/query analogue. SQL text and errors are
        visible only to the query's owner (other users see state-level
        metadata, the reference's query-details access rule)."""
        out = []
        user = getattr(identity, "user", None)
        for job in list(self._jobs.values()):
            with job.lock:
                visible = (
                    identity is None or job.user is None or job.user == user
                )
                out.append(
                    {
                        "id": job.query_id,
                        "state": job.state,
                        "rows": len(job.rows),
                        "sql": job.sql[:200] if visible else None,
                        "error": job.error if visible else None,
                    }
                )
        return out

    # completed-job retention (QueryTracker TTL analogue,
    # main/execution/QueryTracker.java): evict after TTL or beyond a cap,
    # oldest first — an unbounded _jobs map leaks in a long-lived server.
    # The cap only evicts DRAINED jobs (final page delivered); a client
    # mid-pagination is protected until the TTL, which bounds abandoned
    # queries regardless.
    COMPLETED_TTL_S = 300.0
    MAX_COMPLETED = 200
    # abandoned-query expiry (QueryTracker.failAbandonedQueries analogue,
    # main/execution/QueryTracker.java + query.client.timeout): a live
    # query whose client stopped polling fails after this long so it
    # cannot pin results/resources forever
    CLIENT_TTL_S = 300.0

    def _evict_completed(self) -> None:
        now = time.monotonic()
        for qid, j in list(self._jobs.items()):
            # age from the LATER of finish and last client poll: a client
            # still paginating keeps refreshing last_heartbeat and must
            # not lose its remaining pages to the hard pop
            last_activity = max(
                j.finished_at or 0.0, j.last_heartbeat
            )
            if (
                j.finished_at is not None
                and now - last_activity > self.COMPLETED_TTL_S
            ):
                self._jobs.pop(qid, None)
                continue
            with j.lock:
                if (
                    j.finished_at is None
                    and now - j.last_heartbeat > self.CLIENT_TTL_S
                ) or (
                    j.state == "finished"
                    and not j.drained
                    and now - j.last_heartbeat > self.CLIENT_TTL_S
                ):
                    j.abandoned = True
                    j.state = "failed"
                    j.error = (
                        "Query abandoned: no client heartbeat for "
                        f"{self.CLIENT_TTL_S:g}s"
                    )
                    j.rows = []
                    j.finished_at = now
                    j.drained = True
        drained = sorted(
            (j.finished_at, qid)
            for qid, j in list(self._jobs.items())
            if j.finished_at is not None and j.drained
        )
        if len(drained) > self.MAX_COMPLETED:
            for _, qid in drained[: len(drained) - self.MAX_COMPLETED]:
                self._jobs.pop(qid, None)

    def _kill(self, query_id: str) -> None:
        """Client cancel (DELETE /v1/statement/executing/{id}): mark the
        job dead instead of dropping it. A QUEUED job's admission wait
        observes `abandoned` and withdraws its ticket — the queue slot
        is released and the query never runs (and never counts toward
        `running`); a RUNNING job keeps executing to completion but its
        result is discarded and the verdict preserved."""
        job = self._jobs.get(query_id)
        if job is None:
            return
        with job.lock:
            if job.finished_at is not None:
                return  # already terminal: keep the real verdict
            job.abandoned = True
            job.state = "failed"
            job.error = "Query killed by user (DELETE)"
            job.finished_at = time.monotonic()
            job.drained = True

    def _submit(self, sql: str, identity=None, transaction_id="NONE",
                prepared=None) -> _QueryJob:
        from trino_tpu.runtime.metrics import METRICS
        from trino_tpu.serving.admission import fast_path_probe

        self._evict_completed()
        # synchronous shed point, BEFORE a job exists: cached-plan point
        # lookups ride the short fast lane, everything else the general
        # lane; a full lane raises OverloadSheddedError (HTTP 429) here
        # on the request thread
        reservation = self.admission.reserve(
            fast=fast_path_probe(self.runner, sql, prepared)
        )
        job = _QueryJob(
            uuid.uuid4().hex[:16], sql, getattr(identity, "user", None)
        )
        self._jobs[job.query_id] = job
        METRICS.increment("queries.submitted")

        def run():
            try:
                # resource-group queueing (lane passed as selector
                # source); a DELETE or client-abandon while queued flips
                # job.abandoned and acquire withdraws the ticket — slot
                # released, the query never runs
                self.admission.wait(
                    reservation, user=job.user or "user",
                    cancelled=lambda: job.abandoned,
                )
                with job.lock:
                    if job.abandoned:
                        return  # expired while queued: don't run or revive
                    job.state = "running"
                # query_max_run_time_s covers the QUEUED phase too: a
                # query that burned its whole wall budget waiting for an
                # admission slot fails typed, before launching anything
                run_limit = float(
                    getattr(
                        getattr(self.runner, "session", None),
                        "query_max_run_time_s", 0.0,
                    ) or 0.0
                )
                if run_limit and (
                    time.monotonic() - job.created_at > run_limit
                ):
                    from trino_tpu.runtime.query_tracker import (
                        EXCEEDED_TIME_LIMIT,
                        ExceededTimeLimitError,
                    )

                    raise ExceededTimeLimitError(
                        f"Query {job.query_id} exceeded the maximum run "
                        f"time limit of {run_limit}s while queued "
                        f"[{EXCEEDED_TIME_LIMIT}]"
                    )
                kwargs = dict(
                    identity=identity, transaction_id=transaction_id,
                    prepared=prepared or None,
                )
                # abandonment reaches INTO the running query: runners
                # that take `cancel` poll it per result page / scheduling
                # round and tear down tasks + memory when it flips
                import inspect

                try:
                    if "cancel" in inspect.signature(
                        self.runner.execute
                    ).parameters:
                        kwargs["cancel"] = lambda: job.abandoned
                except (TypeError, ValueError):
                    pass
                result = None
                # resident fast lane first: a pinned point lookup is a
                # device probe — faster than even a batched execution,
                # and a None falls through unchanged
                from trino_tpu.resident.fastlane import (
                    try_resident_lookup,
                )

                result = try_resident_lookup(
                    self.runner, sql, identity=identity,
                    prepared=prepared or None,
                )
                if result is None and self.batcher is not None:
                    # point lookups coalesce onto one shared device step
                    # (None = not batchable: normal execution below)
                    result = self.batcher.submit(
                        sql, identity=identity, prepared=prepared or None
                    )
                if result is None:
                    result = self.runner.execute(sql, **kwargs)
                with job.lock:
                    if job.abandoned:
                        return  # expired while executing: keep the verdict
                    job.columns = [
                        {"name": n, "type": str(t)}
                        for n, t in zip(result.column_names, result.column_types)
                    ]
                    job.rows = result.rows
                    job.added_prepare = getattr(
                        result, "added_prepare", None
                    )
                    job.deallocated_prepare = getattr(
                        result, "deallocated_prepare", None
                    )
                    job.started_transaction_id = getattr(
                        result, "started_transaction_id", None
                    )
                    job.cleared_transaction = getattr(
                        result, "cleared_transaction", False
                    )
                    job.state = "finished"
                    job.finished_at = time.monotonic()
                METRICS.increment("queries.finished")
            except Exception as e:
                METRICS.increment("queries.failed")
                with job.lock:
                    if job.abandoned:
                        return
                    job.error = str(e)
                    job.state = "failed"
                    job.finished_at = time.monotonic()
                    # TransactionManager prunes the transaction even when
                    # COMMIT/ROLLBACK fail — tell the client its id is
                    # dead or every later statement wedges on it
                    head = sql.lstrip().upper()
                    if head.startswith("COMMIT") or head.startswith("ROLLBACK"):
                        job.cleared_transaction = True
            finally:
                self.admission.release(reservation)

        self._pool.submit(run)
        return job

    def _response(self, job: _QueryJob, token: int) -> dict:
        state, columns, data, total, error = job.snapshot(token)
        out = {
            "id": job.query_id,
            "stats": {"state": state.upper()},
        }
        if state == "failed":
            out["error"] = {"message": error}
            if job.cleared_transaction:
                out["clearedTransactionId"] = True
            job.drained = True  # error delivered: cap-evictable
            return out
        if state != "finished":
            out["nextUri"] = f"{self.uri}/v1/statement/executing/{job.query_id}/{token}"
            return out
        out["columns"] = columns
        if job.added_prepare:
            out["addedPrepare"] = {
                "name": job.added_prepare[0], "sql": job.added_prepare[1],
            }
        if job.deallocated_prepare:
            out["deallocatedPrepare"] = job.deallocated_prepare
        if job.started_transaction_id:
            out["startedTransactionId"] = job.started_transaction_id
        if job.cleared_transaction:
            out["clearedTransactionId"] = True
        if data:
            out["data"] = data
        next_token = token + len(data)
        if next_token < total:
            out["nextUri"] = (
                f"{self.uri}/v1/statement/executing/{job.query_id}/{next_token}"
            )
        else:
            job.drained = True  # final page delivered: cap-evictable
        return out

    def stop(self) -> None:
        self._reaper_stop.set()
        self._reaper.join(2)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._pool.shutdown(wait=False)
