"""Chunk-granular weighted-fair mesh scheduling with park/resume.

A sub-mesh is a single-program resource: two chunk loops interleaving
collectives on one device set deadlock their rendezvous, so PR 17
serialized mesh runs on a bare per-replica `exec_lock` — and its
coordinator-tick profile showed the serving tail is pure queueing on
that lock (exec_lock waits p50 5.4 s vs tick p95 256 µs). The seed's
resource groups only gate *admission*: once a query holds the mesh it
runs to completion, so a q72-class analytic streaming chunks starves
every point lookup behind it.

This module is the missing scheduler between those two layers. The
chunk loop (PR 10) hands the host control at every chunk boundary;
the MeshScheduler decides, at each boundary, whether the holder keeps
the mesh or hands it over:

- **weighted fairness** — per resource group virtual-time accounting
  (the stride-scheduling idiom of runtime/resource_groups.py applied
  at device level): each completed chunk charges `dt / weight` to the
  holder's group; a waiting group whose virtual time lags the holder's
  gets the next slice. An idle group rejoins at the current global
  pass, so sleeping never banks credit (no starvation of the busy
  groups, no unbounded catch-up burst).
- **fast lane** — micro point lookups (serving/admission.py
  classification) are granted ahead of any analytic waiter, and their
  arrival *preempts* the running analytic at the next boundary.
- **park/resume** — a preempted analytic is *parked*: its device
  carries snapshot to the host-side MeshCheckpointStore (the PR 14/17
  checkpoint machinery, accounted against `park_max_bytes`), device
  memory is released, and the query resumes later from chunk k on the
  same warm ladder rungs — zero re-executed chunk-steps, zero new XLA
  lowerings, byte-identical output. When the program is unparkable
  (uncacheable identity, unchunked) the preemption degrades to an
  in-place yield (carries stay resident, the grant rotates); when the
  park budget refuses the snapshot the query simply runs to
  completion — degradation is never query failure.
- **bounded slice** — the holder always runs at least
  `min_slice_chunks` between preemptions, so a continuous fast-lane
  stream cannot live-lock the analytic.

Typed lifecycle composes with parked state: the wait loops poll the
caller's preemption hook (deadline / abandonment — a parked query that
exceeds its budget dies typed and never resumes) and the replica drain
check (a drain surfacing while parked raises MeshReplicaDraining out
of the parked wait; the parked checkpoint is host-portable, so the
query resumes from chunk k on a sibling sub-mesh).

One scheduler guards one mesh resource: the coordinator owns one for
the full-width mesh; each Replica owns one as its run queue.
"""

from __future__ import annotations

import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import time
from typing import Dict, List, Optional

# /v1/metrics counter names (registered at zero by
# register_scheduler_metrics — same surface protocol as the recovery
# and replica counters)
PARKS = "scheduler.parks"
RESUMES = "scheduler.resumes"
PREEMPTIONS = "scheduler.preemptions"
STEALS = "scheduler.steals"
YIELDS = "scheduler.yields"
PARK_REFUSALS = "scheduler.park_refusals"

_COUNTERS = (PARKS, RESUMES, PREEMPTIONS, STEALS, YIELDS, PARK_REFUSALS)

# wait-loop tick: how often a blocked job re-polls its preemption hook
# (deadline/abandonment) and drain check while queued or parked
_WAIT_TICK_S = 0.02
# cap on the fast-arrival courtesy hold: how long a boundary will pause
# (seat kept) for a submitted-but-still-prepping fast query to become
# ready before streaming resumes. Bounds the damage if the arrival dies
# before ever reaching acquire (finish() wakes the hold early).
_FAST_ARRIVAL_HOLD_S = 0.1

# vtime comparison slack: a waiter must lag the holder by more than
# this before fairness alone rotates the grant (suppresses thrash
# between groups whose accounts are effectively even)
_VTIME_EPS = 1e-9


def register_scheduler_metrics() -> None:
    from trino_tpu.runtime.metrics import METRICS

    for name in _COUNTERS:
        METRICS.increment(name, 0.0)


class MeshJob:
    """One query's seat in a MeshScheduler: identity, lane, group
    accounting hooks, and the blocking park/yield state machine the
    chunk loop drives through `boundary()` / `park_wait()`."""

    # states: waiting -> running -> (waiting | parked -> running)* -> done
    def __init__(self, scheduler: "MeshScheduler", query_id: str,
                 group: str, weight: float, fast: bool, seq: int,
                 poll=None):
        self.scheduler = scheduler
        self.query_id = query_id
        self.group = group
        self.weight = max(float(weight), 1e-6)
        self.fast = bool(fast)
        self.seq = seq
        # poll(done, total): the coordinator's preemption hook —
        # latched deadline kills / client abandonment fire typed OUT OF
        # the wait loops, so a queued or parked query never outlives
        # its budget just because it isn't running
        self.poll = poll
        # aux_check(): replica drain hook; raises MeshReplicaDraining
        # when the mesh under this job leaves rotation
        self.aux_check = None
        self.state = "waiting"
        # ready: the job is blocked in acquire() and can use a grant
        # RIGHT NOW. Jobs are submitted before their host planning and
        # feed builds run (so the fast lane sees arrivals early), but
        # the dispatcher must never seat a query that is still
        # prepping — it would hold the mesh idle against real waiters.
        # Flipped by _wait_for_grant; synthetic waiters (tests, chaos)
        # that never acquire must set it themselves to exert pressure.
        self.ready = False
        self.no_park = False  # latched on park-budget refusal
        self.chunks_in_slice = 0
        self.parked_s = 0.0  # cumulative wall spent parked
        self._park_t0 = None  # start of the park in flight, if any
        self.progress = (0, 0)  # (done, total) for wait-loop polls

    # convenience passthroughs --------------------------------------
    def boundary(self, done: int, total: int, dt: float,
                 parkable: bool = False) -> str:
        return self.scheduler.boundary(self, done, total, dt, parkable)

    def park_wait(self, done: int, total: int) -> None:
        self.scheduler.park_wait(self, done, total)

    def park_refused(self) -> None:
        self.scheduler.park_refused(self)


class MeshScheduler:
    """Weighted-fair run queue over one mesh resource.

    Counters are INSTANCE-scoped (the EXPLAIN `scheduler=` line reads
    them deterministically) and mirrored into the process-global
    METRICS registry for /v1/metrics."""

    def __init__(self, name: str = "mesh", min_slice_chunks: int = 1,
                 preemption_enabled: bool = True,
                 weights: Optional[Dict[str, float]] = None):
        self.name = name
        self.min_slice_chunks = max(1, int(min_slice_chunks))
        self.preemption_enabled = bool(preemption_enabled)
        self.weights = dict(weights or {})
        self._lock = named_lock("MeshScheduler._lock")
        self._cond = threading.Condition(self._lock)
        self._holder: Optional[MeshJob] = None  # guarded_by: _lock
        self._waiting: List[MeshJob] = []  # guarded_by: _lock
        self._seq = 0  # guarded_by: _lock
        # per-group virtual time (stride scheduling: vtime grows by
        # chunk_wall / weight; the group with the smallest account runs)
        self._vtime: Dict[str, float] = {}  # guarded_by: _lock
        self._gpass = 0.0  # guarded_by: _lock — high-water pass idle groups rejoin at
        # instance counters (EXPLAIN line) — mirrored to METRICS
        self.parks = 0
        self.resumes = 0
        self.preemptions = 0
        self.yields = 0
        self.park_refusals = 0
        self.submitted = 0
        self.fast_submitted = 0  # fast-lane share of `submitted`
        self.fast_holds = 0
        register_scheduler_metrics()

    # -- submission / grant lifecycle --------------------------------
    def submit(self, query_id: str, group: str = "default",
               weight: Optional[float] = None, fast: bool = False,
               poll=None) -> MeshJob:
        """Enqueue a query. `weight` defaults to the scheduler's
        per-group weight table (scheduling_weight analogue), else 1."""
        with self._lock:
            self._seq += 1
            w = weight if weight is not None else self.weights.get(group, 1.0)
            job = MeshJob(self, query_id, group, w, fast, self._seq, poll)
            # rejoin-at-current-pass starvation guard: an idle group
            # must not have banked credit while it slept
            v = self._vtime.get(job.group)
            self._vtime[job.group] = (
                self._gpass if v is None else max(v, 0.0)
            )
            self._waiting.append(job)
            self.submitted += 1
            if job.fast:
                self.fast_submitted += 1
            self._cond.notify_all()
            return job

    def acquire(self, job: MeshJob, aux_check=None) -> None:
        """Block until the mesh is granted to `job`. The wait loop
        polls the job's preemption hook and the drain check, so queued
        queries die typed (deadline/abandonment) or fail over (drain)
        instead of waiting out a grant they can never use."""
        if aux_check is not None:
            job.aux_check = aux_check
        self._wait_for_grant(job)

    def finish(self, job: MeshJob) -> None:
        """Release the job's seat whatever state it died or finished
        in; the next grant dispatches immediately."""
        with self._lock:
            job.state = "done"
            if self._holder is job:
                self._holder = None
            if job in self._waiting:
                self._waiting.remove(job)
            self._dispatch_locked()
            self._cond.notify_all()

    # -- chunk-boundary protocol -------------------------------------
    def boundary(self, job: MeshJob, done: int, total: int, dt: float,
                 parkable: bool = False) -> str:
        """Called by the chunk loop after each completed chunk-step.
        Charges `dt / weight` to the holder's group, then decides:

        - "run"  — keep the mesh (possibly after an in-place yield to
          a lagging group or an unparkable fast preemption: the call
          blocks through the handover and returns once regranted);
        - "park" — a fast-lane waiter preempts and the program can
          park: the caller snapshots its carries, drops device refs,
          and calls park_wait().
        """
        from trino_tpu.runtime.metrics import METRICS

        wants_yield = False
        with self._lock:
            if self._holder is not job:
                return "run"  # not holding (width-1 bypass): no-op
            self._charge_locked(job, dt)
            job.chunks_in_slice += 1
            job.progress = (done, total)
            if not self._waiting or done >= total:
                return "run"
            if job.chunks_in_slice < self.min_slice_chunks:
                return "run"
            # only READY waiters exert preemption pressure: parking for
            # a query still in host prep would idle the mesh
            fast_waiter = any(w.fast and w.ready for w in self._waiting)
            holder_v = self._vtime.get(job.group, 0.0)
            lagging = any(
                w.ready
                and (not w.fast)
                and w.group != job.group
                and self._vtime.get(w.group, 0.0)
                < holder_v - _VTIME_EPS
                for w in self._waiting
            )
            if not fast_waiter and not lagging:
                fast_waiter = self._hold_for_fast_arrival_locked()
                if not fast_waiter:
                    return "run"
            self.preemptions += 1
            if (
                fast_waiter
                and self.preemption_enabled
                and parkable
                and not job.no_park
            ):
                METRICS.increment(PREEMPTIONS)
                return "park"
            # in-place yield: rotate the grant, carries stay resident
            self.yields += 1
            self._release_locked(job)
            wants_yield = True
        METRICS.increment(PREEMPTIONS)
        if wants_yield:
            METRICS.increment(YIELDS)
            self._wait_for_grant(job)
        return "run"

    def park_wait(self, job: MeshJob, done: int, total: int) -> None:
        """The caller has snapshotted its carries and released device
        memory: give up the grant, count the park, and block until
        regranted. Typed kills and drain checks fire out of the wait;
        the caller owns checkpoint cleanup on either exit."""
        from trino_tpu.runtime.metrics import METRICS

        t0 = time.monotonic()
        with self._lock:
            self.parks += 1
            job.progress = (done, total)
            job.state = "parked"
            job._park_t0 = t0
            self._release_locked(job)
        METRICS.increment(PARKS)
        try:
            self._wait_for_grant(job)
        finally:
            job.parked_s += time.monotonic() - t0
            job._park_t0 = None
        with self._lock:
            self.resumes += 1
        METRICS.increment(RESUMES)

    def park_budget_for(self, job: MeshJob, total_bytes: int) -> int:
        """Admission-weighted park budget: `total_bytes` (the
        mesh_park_max_bytes pool) apportioned across the groups this
        scheduler has seen by their scheduling weight — the park-store
        analogue of the vtime share. A group over its share gets its
        park refused (the chunk loop degrades to an in-place yield via
        the latched no_park, never to failure). A single-group
        scheduler keeps the whole pool; an unbounded pool (< 0) passes
        through."""
        if total_bytes < 0:
            return int(total_bytes)
        with self._lock:
            groups = set(self._vtime) | set(self.weights) | {job.group}
            if len(groups) <= 1:
                return int(total_bytes)
            wsum = sum(self.weights.get(g, 1.0) for g in groups)
            share = (
                self.weights.get(job.group, 1.0) / wsum
                if wsum > 0 else 1.0
            )
        return int(total_bytes * share)

    def park_refused(self, job: MeshJob) -> None:
        """The park budget refused the snapshot: latch no_park so the
        scheduler stops proposing parks — the query runs to completion
        (degradation is never query failure)."""
        from trino_tpu.runtime.metrics import METRICS

        with self._lock:
            job.no_park = True
            self.park_refusals += 1
        METRICS.increment(PARK_REFUSALS)

    # -- internals ---------------------------------------------------
    def _hold_for_fast_arrival_locked(self) -> bool:
        """Fast-arrival courtesy hold (runs under self._lock; returns
        whether a READY fast waiter now exists). A fast query has been
        submitted but is still in host prep, so it can't take a grant
        yet — but streaming more chunks at full speed would convoy its
        planning behind this loop's per-chunk dispatch work (the prep
        is pure host code contending for the interpreter). Pause at
        THIS boundary instead, seat kept: cond.wait drops the lock, the
        arrival preps at solo speed, and the park/yield handoff happens
        here rather than several chunk gaps later. Bounded by
        _FAST_ARRIVAL_HOLD_S; a prep that dies before acquire wakes the
        hold via finish()'s notify."""
        if not any(w.fast and not w.ready for w in self._waiting):
            return False
        self.fast_holds += 1
        deadline = time.monotonic() + _FAST_ARRIVAL_HOLD_S
        while time.monotonic() < deadline:
            if any(w.fast and w.ready for w in self._waiting):
                return True
            if not any(w.fast and not w.ready for w in self._waiting):
                return False  # arrival died (or was granted elsewhere)
            self._cond.wait(0.002)
        return any(w.fast and w.ready for w in self._waiting)

    def _charge_locked(self, job: MeshJob, dt: float) -> None:
        g = job.group
        v = self._vtime.get(g, self._gpass) + max(dt, 0.0) / job.weight
        self._vtime[g] = v
        self._gpass = max(self._gpass, v)

    def _release_locked(self, job: MeshJob) -> None:
        if self._holder is job:
            self._holder = None
        if job.state != "parked":
            job.state = "waiting"
        if job not in self._waiting:
            self._waiting.append(job)
        self._dispatch_locked()
        self._cond.notify_all()

    def _pick_locked(self) -> Optional[MeshJob]:
        ready = [w for w in self._waiting if w.ready]
        if not ready:
            return None
        fast = [w for w in ready if w.fast]
        if fast:
            return min(fast, key=lambda w: w.seq)  # fast lane: FIFO
        return min(
            ready,
            key=lambda w: (self._vtime.get(w.group, 0.0), w.seq),
        )

    def _dispatch_locked(self) -> None:
        if self._holder is not None:
            return
        nxt = self._pick_locked()
        if nxt is None:
            return
        self._waiting.remove(nxt)
        # rejoin-at-current-pass: a group granted after lagging far
        # behind must not monopolize the mesh paying back history
        self._vtime[nxt.group] = max(
            self._vtime.get(nxt.group, 0.0), 0.0
        )
        nxt.state = "running"
        nxt.chunks_in_slice = 0
        self._holder = nxt

    def _wait_for_grant(self, job: MeshJob) -> None:
        """Block until `job` holds the mesh, polling its typed-kill and
        drain hooks every tick. On a hook raise the seat is released
        (the job will never run) and the error propagates."""
        job.ready = True
        while True:
            with self._lock:
                if self._holder is None:
                    self._dispatch_locked()
                if self._holder is job:
                    job.state = "running"
                    return
                self._cond.wait(_WAIT_TICK_S)
                if self._holder is job:
                    job.state = "running"
                    return
            try:
                if job.poll is not None:
                    done, total = job.progress
                    # live parked wall: a kill DURING the first park
                    # must already carry the parked context, not just
                    # kills after a completed park/resume cycle
                    parked = job.parked_s
                    t0 = job._park_t0
                    if t0 is not None:
                        parked += time.monotonic() - t0
                    try:
                        job.poll.parked_s = parked
                    except AttributeError:
                        pass  # bare-callable hooks (tests) are fine
                    job.poll(done, total)
                if job.aux_check is not None:
                    job.aux_check()
            except BaseException:
                self.finish(job)
                raise

    # -- observability -----------------------------------------------
    def waiting_count(self, fast: Optional[bool] = None) -> int:
        """READY waiters only — a submitted job still in host prep is
        not waiting for the mesh yet (park-forcing pollers rely on
        this: once the count is visible, the next boundary parks)."""
        with self._lock:
            if fast is None:
                return len([w for w in self._waiting if w.ready])
            return len([
                w for w in self._waiting if w.ready and w.fast == fast
            ])

    def holder_query(self) -> Optional[str]:
        with self._lock:
            return None if self._holder is None else self._holder.query_id

    def stats(self) -> dict:
        with self._lock:
            return {
                "submitted": self.submitted,
                "fast_submitted": self.fast_submitted,
                "parks": self.parks,
                "resumes": self.resumes,
                "preemptions": self.preemptions,
                "yields": self.yields,
                "park_refusals": self.park_refusals,
                "fast_holds": self.fast_holds,
                "waiting": len(self._waiting),
                "vtime": dict(self._vtime),
            }


def parse_group_weights(spec: str) -> Dict[str, float]:
    """`mesh_scheduler_weights` session property: "etl=1,serving=4"
    (scheduling_weight analogue). Malformed entries are skipped — a
    typo must not fail query dispatch."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        try:
            w = float(val.strip())
        except ValueError:
            continue
        if name.strip() and w > 0:
            out[name.strip()] = w
    return out
