"""Replicated serving meshes: carve the device set into sub-meshes.

The mesh plane (parallel/mesh_plan.py) runs one named-axis mesh over
every visible device — one fault domain, one queue. This module is the
GSPMD scale-out half (SNIPPETS [3]: "from 8-chip pods to 6000-chip
superclusters without changing application code"): the device set
becomes a 2-D `replica` x `partition` grid, each row an identical
sub-mesh running the SAME prelude/step/flush `jit(shard_map)` programs
unchanged — the programs only ever see their row's 1-D `shard` axis.

The ReplicaManager is the coordinator's placement layer over that grid:

- **health**: each replica carries a CircuitBreaker (the per-node
  graylist of runtime/discovery.py, applied to a fault domain instead
  of a worker). Mesh-run failures trip it; a later success closes it;
  an open breaker sits out `cooldown_s` before a half-open probe
  placement may try the replica again.
- **placement**: `place()` picks the least-loaded healthy replica
  (round-robin on ties), so admission lanes spread across sub-meshes.
  Plan/program caches are process-global, so a query landing on any
  replica reuses warm rungs — each replica pays its own device-set
  lowering once, then stays warm. A sub-mesh executes ONE mesh program
  at a time (interleaved collectives from two programs on one device
  set deadlock their rendezvous), so replicas are also the serving
  tier's units of mesh concurrency.
- **lifecycle**: `request_drain` flips a replica to shutting_down; new
  placements skip it immediately and its in-flight chunk loops raise
  MeshReplicaDraining at the next boundary, handing the query to the
  coordinator's failover dispatch.
- **failover**: the dying replica's chunked queries resume on a sibling
  from the host-portable checkpoint store (recovery/checkpoint.py) —
  keyed by program identity minus device identity, so the sibling's
  ChunkedMeshRunner finds the snapshot as its own.

Multi-host: `maybe_initialize_distributed()` joins the jax.distributed
pod when the standard coordinator env vars are present; single-process
runs (tests, CPU CI) skip it entirely.
"""

from __future__ import annotations

import os
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from trino_tpu.runtime.discovery import CircuitBreaker

# /v1/metrics counter names (registered at zero by
# register_replica_metrics so the surface is visible before the first
# replica event — same protocol as the recovery counters)
PLACEMENTS = "replica.placements"
FAILOVERS = "replica.failovers"
DRAINS = "replica.drains"
BREAKER_OPENS = "replica.breaker_opens"

_COUNTERS = (PLACEMENTS, FAILOVERS, DRAINS, BREAKER_OPENS)

_DISTRIBUTED_INITIALIZED = False


def register_replica_metrics() -> None:
    from trino_tpu.runtime.metrics import METRICS

    for name in _COUNTERS:
        METRICS.increment(name, 0.0)


def maybe_initialize_distributed() -> bool:
    """Join the jax.distributed pod when launched under a multi-host
    coordinator (JAX_COORDINATOR_ADDRESS + process env, the standard
    jax.distributed.initialize() auto-detection inputs). Idempotent and
    deliberately quiet on single-process runs: the CPU CI mesh and
    every test build replicas out of the local device set alone."""
    global _DISTRIBUTED_INITIALIZED
    if _DISTRIBUTED_INITIALIZED:
        return True
    if not os.environ.get("JAX_COORDINATOR_ADDRESS"):
        return False
    try:
        import jax

        jax.distributed.initialize()
        _DISTRIBUTED_INITIALIZED = True
        return True
    except Exception:
        return False


class Replica:
    """One sub-mesh row of the replica x partition grid: its device
    slice, breaker-tracked health, lifecycle state and live depth."""

    def __init__(self, replica_id: int, devices: Sequence,
                 breaker: CircuitBreaker, scheduler_kw=None):
        self.replica_id = replica_id
        self.devices = list(devices)
        self.breaker = breaker
        # a sub-mesh is a single-program resource: two chunk loops
        # interleaving collectives on the SAME device set deadlock the
        # cross-module rendezvous (each program's AllToAll waits for
        # participants the other program occupies). Mesh runs serialize
        # on this lock per replica — REPLICAS are the serving tier's
        # units of mesh concurrency, not threads on one mesh.
        self.exec_lock = named_lock("Replica.exec_lock")
        # the replica's run queue (runtime/scheduler.py): the same
        # single-program guarantee as exec_lock, but chunk-granular —
        # the holder's chunk loop consults the scheduler at every
        # boundary, so fast-lane arrivals preempt (park) the running
        # analytic instead of queueing behind its whole run. The
        # coordinator routes through this when mesh_scheduler is on,
        # and through the bare exec_lock otherwise.
        from trino_tpu.runtime.scheduler import MeshScheduler

        self.scheduler = MeshScheduler(
            name=f"replica-{replica_id}", **(scheduler_kw or {})
        )
        # active -> shutting_down (drain requested: no new placements,
        # in-flight chunk loops fail over at the next boundary) ->
        # drained (nothing in flight; decommissionable). "left" is the
        # heartbeat tier's verdict (host lost / flapped — see
        # ReplicaManager.leave): out of the placement pool like a
        # drain, but recoverable through join() under a new epoch.
        self.state = "active"
        # membership epoch this replica (re)joined under; a rejoin
        # after a flap moves it, which is what fences stale resumes
        self.join_epoch = 0
        self.inflight = 0
        self.served = 0  # lifetime placements onto this replica


class ReplicaManager:
    """Placement + health + failover bookkeeping over N identical
    sub-meshes. Counters are INSTANCE-scoped (deterministic per runner,
    the EXPLAIN `replicas=` line reads them) and mirrored into the
    process-global METRICS registry for /v1/metrics."""

    def __init__(self, n_replicas: int, devices=None,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 1.0,
                 scheduler_kw=None):
        import jax

        maybe_initialize_distributed()
        devs = list(devices) if devices is not None else list(jax.devices())
        if n_replicas < 1:
            raise ValueError(f"mesh_replicas must be >= 1, got {n_replicas}")
        per = len(devs) // n_replicas
        if per < 1:
            raise ValueError(
                f"mesh_replicas={n_replicas} needs at least one device "
                f"per replica ({len(devs)} visible)"
            )
        # the 2-D replica x partition grid; row r is replica r's
        # sub-mesh. Leftover devices (len % n) stay out of the grid so
        # every replica is identical — identical widths are what make
        # checkpoints portable between them (carry shapes are (n*cap,))
        self.grid = np.array(devs[: n_replicas * per]).reshape(
            n_replicas, per
        )
        self.n_replicas = n_replicas
        self.partition_width = per
        self._lock = named_lock("ReplicaManager._lock")
        self._rr = 0  # guarded_by: _lock — round-robin tiebreak cursor
        self.placements = 0
        self.failovers = 0
        self.drains = 0
        self.breaker_opens = 0
        # -- live membership (runtime/fabric.py drives this) ----------
        # monotonic: every join or leave advances it; resumes carry the
        # epoch their checkpoint context was taken under and
        # require_epoch fences the ones whose target moved on
        self.membership_epoch = 1
        self.joins = 0
        self.leaves = 0
        self.epoch_fences = 0
        # exactly-one-owner ledger: query_id -> (replica_id, epoch) of
        # the single replica allowed to run it right now — a flapped
        # host must never end up racing the sibling that took over
        self._owners: Dict[str, tuple] = {}  # guarded_by: _lock
        self.replicas = [
            Replica(
                r, list(self.grid[r]),
                CircuitBreaker(
                    breaker_threshold, breaker_cooldown_s,
                    on_open=self._on_breaker_open,
                ),
                scheduler_kw=scheduler_kw,
            )
            for r in range(n_replicas)
        ]
        for rep in self.replicas:
            rep.join_epoch = self.membership_epoch
        register_replica_metrics()
        from trino_tpu.runtime.fabric import register_fabric_metrics

        register_fabric_metrics()
        from trino_tpu.runtime.metrics import METRICS

        for rep in self.replicas:
            METRICS.register_gauge(
                f"replica.{rep.replica_id}.queue_depth",
                lambda rep=rep: float(rep.inflight),
            )

    def _on_breaker_open(self) -> None:
        from trino_tpu.runtime.metrics import METRICS

        self.breaker_opens += 1
        METRICS.increment(BREAKER_OPENS)

    def global_mesh(self):
        """The full 2-D named-axis view (`replica` x `partition`-as-
        `shard`) — what a pod-wide collective would address. Sub-mesh
        programs never see it; it exists so the grid carving is
        expressible as one jax Mesh."""
        from jax.sharding import Mesh

        from trino_tpu.parallel.mesh_plan import AXIS, REPLICA_AXIS

        return Mesh(self.grid, (REPLICA_AXIS, AXIS))

    # -- placement ----------------------------------------------------
    def _candidates(self, exclude) -> List[Replica]:
        """Healthy first (active + breaker closed), then cooled-down
        half-open probes, then any active replica — degrade rather than
        refuse, mirroring the coordinator's _schedulable_workers."""
        active = [
            r for r in self.replicas
            if r.state == "active" and r.replica_id not in exclude
        ]
        for r in active:
            r.breaker.mark_probing()
        closed = [r for r in active if not r.breaker.is_open]
        if closed:
            return closed
        probing = [r for r in active if r.breaker.state == "half_open"]
        return probing or active

    def place(self, exclude=()) -> Optional[Replica]:
        """Pick the least-loaded healthy replica not in `exclude` (the
        failover loop excludes replicas it already tried this query).
        None when every replica is excluded or draining — the caller
        falls back to the page plane. Bumps the placement counters and
        the replica's depth; callers MUST release() in a finally."""
        from trino_tpu.runtime.metrics import METRICS

        with self._lock:
            cands = self._candidates(set(exclude))
            if not cands:
                return None
            depth = min(r.inflight for r in cands)
            tied = [r for r in cands if r.inflight == depth]
            rep = tied[self._rr % len(tied)]
            self._rr += 1
            rep.inflight += 1
            rep.served += 1
            self.placements += 1
        METRICS.increment(PLACEMENTS)
        return rep

    def release(self, replica: Replica) -> None:
        with self._lock:
            replica.inflight = max(0, replica.inflight - 1)

    def note_failover(self, from_replica: Replica,
                      to_replica: Optional[Replica] = None) -> None:
        from trino_tpu.runtime.metrics import METRICS

        with self._lock:
            self.failovers += 1
        METRICS.increment(FAILOVERS)

    # -- health (error-tracker listener shape, per fault domain) ------
    def report_failure(self, replica: Replica) -> None:
        replica.breaker.record_failure()

    def report_success(self, replica: Replica) -> None:
        replica.breaker.record_success()

    # -- lifecycle ----------------------------------------------------
    def request_drain(self, replica_id: int) -> Replica:
        """Start draining a replica: placements stop targeting it
        immediately, and every in-flight chunk loop on it raises
        MeshReplicaDraining at its next boundary (the drain_check hook
        below), handing those queries to the failover dispatch."""
        from trino_tpu.runtime.metrics import METRICS

        rep = self.replicas[replica_id]
        with self._lock:
            if rep.state in ("shutting_down", "drained"):
                return rep  # already draining: don't double-count
            rep.state = "shutting_down"
            self.drains += 1
        METRICS.increment(DRAINS)
        return rep

    def drain(self, replica_id: int, timeout_s: float = 30.0,
              poll_s: float = 0.01) -> bool:
        """Graceful drain: request + wait until nothing is in flight on
        the replica (its queries finished or failed over). True once
        drained; False on timeout (the replica stays shutting_down —
        still out of rotation)."""
        rep = self.request_drain(replica_id)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if rep.inflight == 0:
                # state transitions happen under _lock everywhere else
                # (request_drain, undrain, leave); an unlocked write here
                # could race an undrain() and resurrect a dead replica.
                with self._lock:
                    if rep.inflight == 0:
                        rep.state = "drained"
                        return True
                continue
            time.sleep(poll_s)
        return rep.inflight == 0

    def undrain(self, replica_id: int) -> None:
        """Return a drained replica to rotation (chaos harness reuse)."""
        rep = self.replicas[replica_id]
        with self._lock:
            rep.state = "active"

    # -- live membership (heartbeat-driven; runtime/fabric.py) --------
    def leave(self, replica_id: int) -> Replica:
        """Heartbeat-driven departure: the replica leaves the placement
        pool under a NEW membership epoch. The Replica object — breaker
        state, lifetime counters — survives, so a flap (leave + rejoin)
        never resets health history. In-flight chunk loops on it fail
        over through the same drain_check boundary hook a drain uses
        (state left the active set)."""
        from trino_tpu.runtime.fabric import LEAVES
        from trino_tpu.runtime.metrics import METRICS

        rep = self.replicas[replica_id]
        with self._lock:
            if rep.state == "left":
                return rep  # already out: don't double-advance the epoch
            rep.state = "left"
            self.membership_epoch += 1
            self.leaves += 1
        METRICS.increment(LEAVES)
        return rep

    def join(self, replica_id: int, warm=None) -> Replica:
        """(Re)admit a replica under a new membership epoch. `warm`
        runs BEFORE the replica enters the placement pool (the
        joining-host warmup replay of runtime/fabric.py: its first
        placed query must mint zero new lowerings); a warm failure
        still joins — warmup delays availability, never gates it."""
        from trino_tpu.runtime.fabric import JOINS
        from trino_tpu.runtime.metrics import METRICS

        rep = self.replicas[replica_id]
        if rep.state == "active":
            return rep
        if warm is not None:
            try:
                warm()
            except Exception:
                pass
        with self._lock:
            self.membership_epoch += 1
            rep.state = "active"
            rep.join_epoch = self.membership_epoch
            self.joins += 1
        METRICS.increment(JOINS)
        return rep

    # -- ownership ledger (exactly one owner per in-flight query) -----
    def claim(self, query_id: str, replica: Replica) -> bool:
        """Record `replica` as the single owner of `query_id` under the
        current epoch. Refused while ANOTHER replica's claim is live —
        even if that replica has since left (its chunk loop may still
        be unwinding), so a membership flap can never double-place a
        query across epochs. Re-claim by the same replica is a no-op
        refresh."""
        if not query_id:
            return True  # anonymous dispatch: nothing to fence
        with self._lock:
            cur = self._owners.get(query_id)
            if cur is not None and cur[0] != replica.replica_id:
                return False
            self._owners[query_id] = (
                replica.replica_id, self.membership_epoch
            )
            return True

    def unclaim(self, query_id: str, replica: Replica) -> None:
        if not query_id:
            return
        with self._lock:
            cur = self._owners.get(query_id)
            if cur is not None and cur[0] == replica.replica_id:
                del self._owners[query_id]

    def owner_of(self, query_id: str):
        """(replica_id, epoch) of the live claim, or None."""
        with self._lock:
            return self._owners.get(query_id)

    def require_epoch(self, replica: Replica, expected_epoch: int) -> None:
        """Fence a resume: refuse (typed MembershipEpochError) when the
        target replica's epoch moved past the one the resume context
        was taken under, or it is no longer active — it left and
        rejoined in between, so carrying the old resume would hand
        stale state to what is effectively a new host. The caller
        discards the checkpoint and restarts fresh."""
        from trino_tpu.runtime.fabric import (
            EPOCH_FENCES,
            MembershipEpochError,
        )
        from trino_tpu.runtime.metrics import METRICS

        with self._lock:
            moved = (
                replica.join_epoch > expected_epoch
                or replica.state != "active"
            )
            if moved:
                self.epoch_fences += 1
        if moved:
            METRICS.increment(EPOCH_FENCES)
            raise MembershipEpochError(
                f"replica {replica.replica_id} membership epoch moved "
                f"({expected_epoch} -> {replica.join_epoch}, "
                f"state={replica.state}): resume refused, restart fresh",
                replica_id=replica.replica_id,
                expected_epoch=expected_epoch,
                actual_epoch=replica.join_epoch,
            )

    def membership_line(self) -> str:
        """The EXPLAIN ANALYZE membership line (instance-scoped, like
        stats_line, so corpus output stays deterministic)."""
        with self._lock:
            return (
                f"membership= epoch={self.membership_epoch} "
                f"joins={self.joins} leaves={self.leaves} "
                f"epoch_fences={self.epoch_fences} "
                f"owners={len(self._owners)}"
            )

    def drain_check(self, replica: Replica):
        """The chunk-boundary hook a MeshExecutor carries: raises
        MeshReplicaDraining (in-run resume disabled) once this replica
        leaves the active state, so the run fails over instead of
        finishing on capacity that is being decommissioned."""
        def check() -> None:
            if replica.state != "active":
                from trino_tpu.parallel.mesh_chunk import (
                    MeshReplicaDraining,
                )

                raise MeshReplicaDraining(
                    f"replica {replica.replica_id} is "
                    f"{replica.state}; failing over at this chunk "
                    "boundary"
                )

        return check

    # -- observability ------------------------------------------------
    def breaker_states(self) -> Dict[int, str]:
        return {r.replica_id: r.breaker.state for r in self.replicas}

    def healthy_count(self) -> int:
        with self._lock:
            return len([
                r for r in self.replicas
                if r.state == "active" and not r.breaker.is_open
            ])

    def stats(self) -> dict:
        with self._lock:
            return {
                "replicas": self.n_replicas,
                "partition_width": self.partition_width,
                "placements": self.placements,
                "failovers": self.failovers,
                "drains": self.drains,
                "breaker_opens": self.breaker_opens,
                "per_replica": {
                    r.replica_id: {
                        "state": r.state,
                        "breaker": r.breaker.state,
                        "depth": r.inflight,
                        "served": r.served,
                    }
                    for r in self.replicas
                },
            }

    def stats_line(self) -> str:
        s = self.stats()
        states = "".join(
            p["state"][0] for p in s["per_replica"].values()
        )
        return (
            f"replicas= n={s['replicas']}x{s['partition_width']} "
            f"states={states} placements={s['placements']} "
            f"failovers={s['failovers']} drains={s['drains']} "
            f"breaker_opens={s['breaker_opens']}"
        )
