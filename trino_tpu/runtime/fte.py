"""Fault-tolerant (task-retry) query scheduling over spooled exchange.

Analogue of EventDrivenFaultTolerantQueryScheduler.java:160 (SURVEY.md
§3.5): stages execute bottom-up; every task's output is spooled through
the external exchange (runtime/spool.py) so tasks are idempotent and
individually re-runnable. On failure a partition is re-launched as
attempt+1 — on a different active worker when one exists (the
BinPackingNodeAllocator's re-placement, reduced to avoid-the-failed-
node) — and consumers read exactly one committed attempt per partition
(ExchangeSourceOutputSelector de-duplication). Workers joining between
rounds are picked up because the active set is re-read per launch
(FTE elasticity, §5.3).
"""

from __future__ import annotations

import itertools
import time
from typing import Dict, List, Optional, Tuple

from trino_tpu.runtime.task import TaskId, TaskSpec
from trino_tpu.sql.fragmenter import SubPlan


class TaskRetriesExceeded(RuntimeError):
    pass


class FaultTolerantQueryScheduler:
    def __init__(
        self,
        query_id: str,
        subplan: SubPlan,
        workers: List,  # worker handles (or a NodeManager via active_fn)
        catalogs,
        session,
        spool_dir: str,
        hash_partitions: Optional[int] = None,
        max_task_retries: int = 3,
        active_workers_fn=None,
    ):
        self.query_id = query_id
        self.subplan = subplan
        self.workers = workers
        self.catalogs = catalogs
        self.session = session
        self.spool_dir = spool_dir
        self.hash_partitions = hash_partitions or min(len(workers), 4)
        self.max_task_retries = max_task_retries
        self._active_fn = active_workers_fn or (lambda: self.workers)
        self._schemas: Dict[int, list] = {}
        # (fragment, partition) -> committed task key
        self.committed: Dict[Tuple[int, int], str] = {}
        self.retries = 0

    # scheduling is stage-by-stage: children complete before parents run
    def run(self) -> Tuple[object, str]:
        """Execute every stage; returns (root worker handle, root task
        key) for result fetching (root output is spooled too, so any
        handle can serve it — we return the one that ran it)."""
        from trino_tpu.runtime.stages import stage_task_count, topo_order

        order = topo_order(self.subplan)
        task_counts = {
            sp.fragment.id: stage_task_count(
                sp, len(self.workers), self.hash_partitions
            )
            for sp in order
        }
        consumer_counts: Dict[int, int] = {}
        for sp in order:
            for c in sp.children:
                consumer_counts[c.fragment.id] = task_counts[sp.fragment.id]
        root_handle = None
        for sp in order:
            root_handle = self._run_stage(
                sp, task_counts[sp.fragment.id],
                consumer_counts.get(sp.fragment.id, 1),
            )
        root_key = self.committed[(self.subplan.fragment.id, 0)]
        return root_handle, root_key

    def _run_stage(self, sp: SubPlan, tc: int, n_out: int):
        from trino_tpu.runtime.stages import fragment_schema

        f = sp.fragment
        remote = {
            c.fragment.id: self._schemas[c.fragment.id] for c in sp.children
        }
        self._schemas[f.id] = fragment_schema(
            self.catalogs, self.session, sp, remote
        )
        input_locations = {
            c.fragment.id: [
                ("spool", self.spool_dir, self.committed[(c.fragment.id, p)])
                for p in range(
                    len([
                        k for k in self.committed if k[0] == c.fragment.id
                    ])
                )
            ]
            for c in sp.children
        }
        pending = {p: 0 for p in range(tc)}  # partition -> attempt
        running: Dict[int, Tuple[object, str]] = {}
        last_handle = None
        avoid: Dict[int, object] = {}  # partition -> failed handle
        while pending or running:
            active = list(self._active_fn())
            if not active:
                raise TaskRetriesExceeded("no active workers")
            # launch
            for p in sorted(pending):
                attempt = pending.pop(p)
                candidates = [w for w in active if w is not avoid.get(p)] or active
                handle = candidates[
                    (p + attempt) % len(candidates)
                ]
                task_id = TaskId(self.query_id, f.id, p, attempt)
                spec = TaskSpec(
                    task_id=task_id,
                    fragment=f,
                    n_output_partitions=n_out,
                    remote_schemas=remote,
                    scan_slice=(p, tc) if f.partitioning == "source" else None,
                    input_locations=input_locations,
                    batch_rows=self.session.batch_rows,
                    target_splits=max(self.session.target_splits, tc),
                    spool_dir=self.spool_dir,
                    dynamic_filtering=self.session.enable_dynamic_filtering,
                )
                try:
                    handle.create_task(spec)
                except Exception as exc:
                    # launch failure == task failure: re-queue on another
                    # node, same retry budget (the status-failure path)
                    if attempt + 1 > self.max_task_retries:
                        raise TaskRetriesExceeded(
                            f"task {task_id} could not launch after "
                            f"{attempt + 1} attempts: {exc}"
                        )
                    self.retries += 1
                    avoid[p] = handle
                    pending[p] = attempt + 1
                    continue
                running[p] = (handle, str(task_id), attempt)
            # poll
            time.sleep(0.01)
            for p, (handle, tid, attempt) in list(running.items()):
                try:
                    st = handle.task_state(tid)
                except Exception as e:
                    st = {"state": "failed", "failure": f"worker unreachable: {e}"}
                if st["state"] == "finished":
                    del running[p]
                    self.committed[(f.id, p)] = tid
                    last_handle = handle
                elif st["state"] == "failed":
                    del running[p]
                    if attempt + 1 > self.max_task_retries:
                        raise TaskRetriesExceeded(
                            f"task {tid} failed after {attempt + 1} attempts: "
                            f"{st.get('failure')}"
                        )
                    self.retries += 1
                    avoid[p] = handle
                    pending[p] = attempt + 1
        return last_handle
