"""Fault-tolerant (task-retry) query scheduling over spooled exchange.

Analogue of EventDrivenFaultTolerantQueryScheduler.java:160 (SURVEY.md
§3.5): stages execute bottom-up; every task's output is spooled through
the external exchange (runtime/spool.py) so tasks are idempotent and
individually re-runnable. On failure a partition is re-launched as
attempt+1 — on a different active worker when one exists (the
BinPackingNodeAllocator's re-placement, reduced to avoid-the-failed-
node) — and consumers read exactly one committed attempt per partition
(ExchangeSourceOutputSelector de-duplication). Workers joining between
rounds are picked up because the active set is re-read per launch
(FTE elasticity, §5.3).
"""

from __future__ import annotations

import itertools
import time
from typing import Dict, List, Optional, Tuple

from trino_tpu.runtime.task import TaskId, TaskSpec
from trino_tpu.sql.fragmenter import SubPlan


class TaskRetriesExceeded(RuntimeError):
    pass


def _quantile(sorted_vals: List[float], q: float) -> float:
    """Linear-interpolated quantile of an ascending list (the numpy
    default method, done by hand — no device round trip for a handful
    of wall times)."""
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    idx = q * (len(sorted_vals) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = idx - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


class _LaunchFailed(Exception):
    def __init__(self, handle, exc):
        self.handle = handle
        self.exc = exc


class FaultTolerantQueryScheduler:
    def __init__(
        self,
        query_id: str,
        subplan: SubPlan,
        workers: List,  # worker handles (or a NodeManager via active_fn)
        catalogs,
        session,
        spool_dir: str,
        hash_partitions: Optional[int] = None,
        max_task_retries: int = 3,
        active_workers_fn=None,
        node_manager=None,
        trace=None,
        query_span=None,
        collect_stats: bool = False,
        deadline_epoch_s: Optional[float] = None,
    ):
        self.query_id = query_id
        self.deadline_epoch_s = deadline_epoch_s
        self.subplan = subplan
        self.workers = workers
        self.catalogs = catalogs
        self.session = session
        self.spool_dir = spool_dir
        self.hash_partitions = hash_partitions or min(len(workers), 4)
        self.max_task_retries = max_task_retries
        self.node_manager = node_manager
        if active_workers_fn is not None:
            self._active_fn = active_workers_fn
        elif node_manager is not None:
            # circuit-breaker-aware placement: graylisted workers get no
            # launches while their breaker is open; if EVERY node is
            # graylisted, fall back to the active set rather than starve
            # (trying a gray node beats failing the query outright)
            self._active_fn = (
                lambda: node_manager.schedulable_workers()
                or node_manager.active_workers()
            )
        else:
            self._active_fn = lambda: self.workers
        self._schemas: Dict[int, list] = {}
        # (fragment, partition) -> committed task key
        self.committed: Dict[Tuple[int, int], str] = {}
        self.retries = 0
        # memory-aware placement (BinPackingNodeAllocatorService +
        # PartitionMemoryEstimator analogues, runtime/node_scheduler.py)
        from trino_tpu.runtime.node_scheduler import (
            BinPackingNodeAllocator,
            PartitionMemoryEstimator,
        )

        self.allocator = BinPackingNodeAllocator(node_manager=node_manager)
        self.estimator = PartitionMemoryEstimator()
        # straggler mitigation: duplicate attempts for tasks running
        # `speculation_quantile`x beyond the stage's PER-FRAGMENT p75
        # (speculation_percentile) of committed-attempt wall times,
        # provided a spare schedulable worker exists; first attempt to
        # commit wins (the one-committed-attempt-per-partition
        # selector), the loser is cancelled cooperatively. The upper
        # quantile beats the old median on skewed stages: half the tasks
        # being "slow-ish" no longer drags the threshold down and
        # triggers duplicate storms.
        self.enable_speculation = getattr(session, "speculation_enabled", True)
        self.speculation_quantile = float(
            getattr(session, "speculation_quantile", 2.0)
        )
        self.speculation_percentile = float(
            getattr(session, "speculation_percentile", 0.75)
        )
        # fragment id -> the quantile wall-time estimate last used to
        # size its straggler threshold (surfaced in last_fte_stats)
        self.speculation_estimates: Dict[int, float] = {}
        self.speculative_hits = 0  # speculative attempts launched
        self.speculation_wins = 0  # ...that committed first
        self.speculation_losses = 0  # ...cancelled or failed
        # task id -> last polled thread-CPU seconds (Worker.task_state
        # "cpu_s"): summed into the query_max_cpu_time_s budget
        self.cpu_by_task: Dict[str, float] = {}
        # "fragment.partition" -> attempts ever launched (observability:
        # chaos/bench assert attempt counts stay bounded per partition)
        self.attempts_per_partition: Dict[str, int] = {}
        self._speculative_tids: set = set()
        # tracing (runtime/tracing.py): one stage span per _run_stage,
        # one task span per attempt (keyed by tid string — the running
        # 5-tuples stay untouched); retry/speculation/deadline/watchdog/
        # chaos events annotate the owning span. collect_stats rides
        # TaskSpec so traced queries get row counts + operator spans.
        self.trace = trace
        self.query_span = query_span
        self.collect_stats = collect_stats
        self._task_spans: Dict[str, object] = {}
        # tid -> (fragment id, last observed status dict) for the
        # QueryInfo stage rollup (losers get a best-effort final fetch
        # in settle, BEFORE remove_task destroys their status)
        self._snapshots: Dict[str, Tuple[int, dict]] = {}

    def _report(self, handle, ok: bool) -> None:
        """Feed the node's circuit breaker: in-process handles have no
        HTTP layer reporting for them, so the scheduler reports its own
        control-plane outcomes (launches, state polls)."""
        if self.node_manager is None:
            return
        wid = getattr(handle, "worker_id", None)
        if wid is None:
            return
        if ok:
            self.node_manager.report_success(wid)
        else:
            self.node_manager.report_failure(wid)

    def cpu_time_s(self) -> float:
        """Query-wide CPU spent, from the last polled per-task ledgers
        (finished/failed attempts keep their final reading)."""
        return sum(self.cpu_by_task.values())

    def task_snapshots(self) -> Dict[int, List[Tuple[str, dict]]]:
        """fragment id -> [(tid, last observed status)] across every
        attempt — the QueryInfo stage-rollup input (same shape as
        QueryScheduler.finalize)."""
        out: Dict[int, List[Tuple[str, dict]]] = {}
        for tid, (fid, st) in self._snapshots.items():
            out.setdefault(fid, []).append((tid, st))
        return out

    def _observe(self, fid: int, tid: str, st: dict) -> None:
        """Record an attempt's latest status; graft its operator spans
        once terminal (the worker only ships spans for terminal tasks;
        graft dedups by span_id so repeat polls are safe)."""
        self._snapshots[tid] = (fid, st)
        if self.trace is not None:
            self.trace.graft(st.get("spans") or [])
            if st.get("state") in ("finished", "failed", "aborted"):
                span = self._task_spans.get(tid)
                if span is not None and not span.ended:
                    if st.get("failure"):
                        # classified failure annotation: a chaos run
                        # must read as one timeline (deadline /
                        # watchdog_interrupt / chaos_fault / task_failed)
                        span.event(self._failure_kind(st["failure"]),
                                   error=str(st["failure"])[:300])
                        span.set(error=True)
                    if st.get("start_time"):
                        span.start_s = st["start_time"]
                    span.set(state=st.get("state"),
                             cpu_s=st.get("cpu_s") or 0.0)
                    span.end(st.get("end_time"))

    @staticmethod
    def _failure_kind(msg: Optional[str]) -> str:
        """Classify a task-failure string into the annotation vocabulary
        (works across HTTP topologies, where only the string travels)."""
        from trino_tpu.runtime.query_tracker import deadline_code

        msg = msg or ""
        if deadline_code(msg) is not None:
            return "deadline"
        if "Stuck task" in msg:
            return "watchdog_interrupt"
        if "injected" in msg.lower():
            return "chaos_fault"
        return "task_failed"

    # scheduling is stage-by-stage: children complete before parents run
    def run(self, cancel=None) -> Tuple[object, str]:
        """Execute every stage; returns (root worker handle, root task
        key) for result fetching (root output is spooled too, so any
        handle can serve it — we return the one that ran it). `cancel`
        is polled between scheduling rounds: client abandonment tears
        the query down instead of finishing work nobody reads."""
        from trino_tpu.runtime.stages import stage_task_count, topo_order

        # recovery tier: a prior attempt (or prior submission) of this
        # plan may have banked complete stage outputs in the subtree
        # spool — replay those as literal sources and skip their whole
        # producer subtrees. Conversely, every stage that settles below
        # records its committed spool files back into the spool so the
        # NEXT attempt after a failure starts further along.
        spooled_ids: set = set()
        record_stages = bool(
            getattr(self.session, "recovery_spool_stages", False)
        )
        if record_stages:
            from trino_tpu.recovery import substitute_spooled_fragments

            new_subplan, hits = substitute_spooled_fragments(
                self.subplan, span=self.query_span
            )
            if hits:
                self.subplan = new_subplan
                spooled_ids = set(hits)

        order = topo_order(self.subplan)
        task_counts = {
            sp.fragment.id: stage_task_count(
                sp, len(self.workers), self.hash_partitions
            )
            for sp in order
        }
        consumer_counts: Dict[int, int] = {}
        for sp in order:
            for c in sp.children:
                consumer_counts[c.fragment.id] = task_counts[sp.fragment.id]
        root_handle = None
        root_id = self.subplan.fragment.id
        for sp in order:
            fid = sp.fragment.id
            n_out = consumer_counts.get(fid, 1)
            root_handle = self._run_stage(
                sp, task_counts[fid], n_out, cancel=cancel,
            )
            if record_stages and fid != root_id and fid not in spooled_ids:
                from trino_tpu.recovery import record_committed_stage

                record_committed_stage(
                    self.spool_dir,
                    [self.committed[(fid, p)]
                     for p in range(task_counts[fid])],
                    sp, n_out, is_root=False,
                )
        root_key = self.committed[(root_id, 0)]
        return root_handle, root_key

    @staticmethod
    def _abort_running(running: Dict[int, List[Tuple]]) -> None:
        """Cooperatively cancel every in-flight attempt (deadline kill /
        abandonment unwind): remove_task flips each task's state machine
        so its driver stops at the next batch boundary and its memory
        contexts close."""
        for entries in running.values():
            for h, tid, _, _, _ in entries:
                try:
                    h.remove_task(tid)
                except Exception:
                    pass

    def _run_stage(self, sp: SubPlan, tc: int, n_out: int, cancel=None):
        from trino_tpu.runtime.stages import fragment_schema

        f = sp.fragment
        stage_span = None
        if self.trace is not None and self.query_span is not None:
            from trino_tpu.runtime.tracing import KIND_STAGE

            stage_span = self.query_span.child(
                f"stage {f.id}", KIND_STAGE, fragment_id=f.id, tasks=tc
            )
        remote = {
            c.fragment.id: self._schemas[c.fragment.id] for c in sp.children
        }
        self._schemas[f.id] = fragment_schema(
            self.catalogs, self.session, sp, remote
        )
        input_locations = {
            c.fragment.id: [
                ("spool", self.spool_dir, self.committed[(c.fragment.id, p)])
                for p in range(
                    len([
                        k for k in self.committed if k[0] == c.fragment.id
                    ])
                )
            ]
            for c in sp.children
        }
        pending = {p: 0 for p in range(tc)}  # partition -> attempt
        # partition -> [(handle, tid, attempt, started_at, est_bytes)];
        # entry 0 is the primary, entry 1 (if any) the speculative dup
        running: Dict[int, List[Tuple]] = {}
        # highest attempt number ever assigned per partition: retry and
        # speculative numbers must never collide with a FAILED attempt's
        # id — create_task is idempotent by id and would hand back the
        # dead TaskExecution
        attempt_hwm: Dict[int, int] = {p: 0 for p in range(tc)}
        durations: List[float] = []  # completed-task wall times
        last_handle = None
        avoid: Dict[int, object] = {}  # partition -> failed handle

        def launch(p: int, attempt: int, avoid_h=None):
            active = list(self._active_fn())
            if not active:
                raise TaskRetriesExceeded("no active workers")
            # memory-aware bin packing; the estimate is re-read PER
            # LAUNCH so register_failure's growth affects the retry
            est_bytes = self.estimator.estimate(f.id)
            handle = self.allocator.acquire(active, est_bytes, avoid=avoid_h)
            attempt_hwm[p] = max(attempt_hwm[p], attempt)
            pkey = f"{f.id}.{p}"
            self.attempts_per_partition[pkey] = (
                self.attempts_per_partition.get(pkey, 0) + 1
            )
            task_id = TaskId(self.query_id, f.id, p, attempt)
            tspan = None
            if stage_span is not None:
                from trino_tpu.runtime.tracing import KIND_TASK, wire_context

                tspan = stage_span.child(
                    f"task {task_id}", KIND_TASK,
                    partition=p, attempt=attempt,
                    worker=getattr(handle, "worker_id", None),
                )
                self._task_spans[str(task_id)] = tspan
            spec = TaskSpec(
                task_id=task_id,
                fragment=f,
                n_output_partitions=n_out,
                remote_schemas=remote,
                scan_slice=(p, tc) if f.partitioning == "source" else None,
                input_locations=input_locations,
                batch_rows=self.session.batch_rows,
                target_splits=max(self.session.target_splits, tc),
                spool_dir=self.spool_dir,
                dynamic_filtering=self.session.enable_dynamic_filtering,
                task_concurrency=self.session.task_concurrency,
                shape_stabilization=getattr(
                    self.session, "shape_stabilization", True
                ),
                capacity_ladder_base=getattr(
                    self.session, "capacity_ladder_base", 2
                ),
                collect_stats=self.collect_stats,
                deadline_epoch_s=self.deadline_epoch_s,
            )
            if tspan is not None and self.collect_stats:
                # operator spans only under query_trace=on: the wire
                # context is what tells the worker to record them
                spec.trace_ctx = wire_context(tspan)
            try:
                handle.create_task(spec)
            except Exception as exc:
                self.allocator.release(handle, est_bytes)
                self._report(handle, ok=False)
                if tspan is not None and not tspan.ended:
                    tspan.event("launch_failed", error=str(exc)[:300])
                    tspan.set(error=True, state="launch_failed")
                    tspan.end()
                raise _LaunchFailed(handle, exc)
            self._report(handle, ok=True)
            return (handle, str(task_id), attempt, time.monotonic(), est_bytes)

        def settle(p: int, winner, losers):
            """Commit the winner; cancel+release live sibling attempts.
            Entries that already FAILED were released in the poll loop
            and must not be passed here (double-release would corrupt
            the allocator's reservations)."""
            handle, tid, _, t0, est = winner
            durations.append(time.monotonic() - t0)
            self.committed[(f.id, p)] = tid
            self.allocator.release(handle, est)
            if tid in self._speculative_tids:
                self.speculation_wins += 1
                wspan = self._task_spans.get(tid)
                if wspan is not None:
                    wspan.event("speculation_won", partition=p)
            for h, other_tid, _, _, other_est in losers:
                self.allocator.release(h, other_est)
                was_speculative = other_tid in self._speculative_tids
                if was_speculative:
                    self.speculation_losses += 1
                lspan = self._task_spans.get(other_tid)
                if lspan is not None:
                    lspan.event(
                        "speculation_lost" if was_speculative
                        else "lost_to_speculation"
                    )
                # last look at the loser's status BEFORE remove_task
                # destroys it: the stage rollup keeps every attempt, and
                # a just-finished loser may have spans worth grafting
                try:
                    self._observe(f.id, other_tid, h.task_state(other_tid))
                except Exception:
                    pass
                if lspan is not None and not lspan.ended:
                    lspan.set(state="aborted")
                    lspan.end()
                # cooperative cancel: remove_task aborts the loser's
                # state machine, so its Driver stops at the next batch
                # boundary; consumers only ever read the committed
                # attempt, so a racing loser cannot add duplicate rows
                try:
                    h.remove_task(other_tid)
                except Exception:
                    pass
            return handle

        while pending or running:
            if cancel is not None and cancel():
                self._abort_running(running)
                raise RuntimeError(
                    f"Query {self.query_id} abandoned: client stopped "
                    "polling results"
                )
            if not list(self._active_fn()):
                raise TaskRetriesExceeded("no active workers")
            # launch
            for p in sorted(pending):
                attempt = pending.pop(p)
                try:
                    running[p] = [launch(p, attempt, avoid.get(p))]
                except _LaunchFailed as lf:
                    # launch failure == task failure: re-queue on another
                    # node, same retry budget (the status-failure path)
                    if attempt + 1 > self.max_task_retries:
                        raise TaskRetriesExceeded(
                            f"task {self.query_id}.{f.id}.{p} could not "
                            f"launch after {attempt + 1} attempts: {lf.exc}"
                        )
                    self.retries += 1
                    avoid[p] = lf.handle
                    pending[p] = attempt_hwm[p] + 1
                    if stage_span is not None:
                        stage_span.event("task_retry", partition=p,
                                         attempt=pending[p],
                                         reason="launch_failed")
            # poll
            time.sleep(0.01)
            now = time.monotonic()
            # straggler threshold: the per-fragment p75 (or whatever
            # speculation_percentile says) of committed wall times. The
            # availability gate is a QUARTER of the stage (min 1): an
            # upper quantile stabilizes on fewer samples than the old
            # median-of-half, so skewed stages speculate sooner — and a
            # 2-task stage must still speculate off its single committed
            # sibling, exactly the case where one straggler IS half the
            # stage.
            est_wall = None
            if len(durations) >= max(1, -(-tc // 4)):
                est_wall = _quantile(
                    sorted(durations), self.speculation_percentile
                )
                self.speculation_estimates[f.id] = est_wall
            for p, entries in list(running.items()):
                finished_entry = None
                next_entries = []
                for entry in entries:
                    handle, tid, attempt, t0, est = entry
                    try:
                        st = handle.task_state(tid)
                        self._report(handle, ok=True)
                    except Exception as e:
                        self._report(handle, ok=False)
                        st = {
                            "state": "failed",
                            "failure": f"worker unreachable: {e}",
                        }
                    if "cpu_s" in st:
                        self.cpu_by_task[tid] = float(st["cpu_s"] or 0.0)
                    self._observe(f.id, tid, st)
                    if st["state"] == "finished":
                        if finished_entry is None:
                            finished_entry = entry
                        else:  # both attempts finished: keep the first
                            next_entries.append(entry)
                        continue
                    if st["state"] == "failed":
                        self.allocator.release(handle, est)
                        fmsg = st.get("failure")
                        from trino_tpu.runtime.query_tracker import (
                            deadline_code,
                            deadline_error,
                        )

                        if deadline_code(fmsg) is not None:
                            # deadline kill: NON-RETRYABLE by contract —
                            # replaying a task of a query whose budget
                            # is spent can only spend it again. Contrast
                            # watchdog interrupts (no code), which stay
                            # in the normal retry path below.
                            if stage_span is not None:
                                stage_span.event("deadline_kill", task=tid)
                            self._abort_running(running)
                            raise deadline_error(f"task {tid}: {fmsg}")
                        if tid in self._speculative_tids:
                            self.speculation_losses += 1
                        self.estimator.register_failure(f.id, fmsg)
                        if len(entries) == 1 and attempt + 1 > self.max_task_retries:
                            raise TaskRetriesExceeded(
                                f"task {tid} failed after {attempt + 1} "
                                f"attempts: {fmsg}"
                            )
                        self.retries += 1
                        avoid[p] = handle
                        continue  # drop this attempt, keep any sibling
                    next_entries.append(entry)
                if finished_entry is not None:
                    last_handle = settle(p, finished_entry, next_entries)
                    del running[p]
                    continue
                if not next_entries:
                    del running[p]
                    next_attempt = attempt_hwm[p] + 1
                    if next_attempt > self.max_task_retries:
                        raise TaskRetriesExceeded(
                            f"partition {p} of fragment {f.id} failed "
                            f"after {next_attempt} attempts"
                        )
                    pending[p] = next_attempt
                    if stage_span is not None:
                        stage_span.event("task_retry", partition=p,
                                         attempt=next_attempt,
                                         reason="task_failed")
                    continue
                running[p] = next_entries
                # speculation: the stage is mostly done, this partition
                # is a straggler, and no duplicate is in flight yet
                if (
                    self.enable_speculation
                    and len(next_entries) == 1
                    and est_wall is not None
                    and now - next_entries[0][3]
                    > max(self.speculation_quantile * est_wall, 0.25)
                    and attempt_hwm[p] < self.max_task_retries
                ):
                    handle = next_entries[0][0]
                    # only speculate when a SPARE worker exists: a dup on
                    # the straggler's own node races the same slowness
                    spare = [
                        h for h in list(self._active_fn()) if h is not handle
                    ]
                    if not spare:
                        continue
                    try:
                        dup = launch(p, attempt_hwm[p] + 1, avoid_h=handle)
                        running[p].append(dup)
                        self.speculative_hits += 1
                        self._speculative_tids.add(dup[1])
                        dspan = self._task_spans.get(dup[1])
                        if dspan is not None:
                            dspan.set(speculative=True)
                            dspan.event("speculative_launch",
                                        straggler=next_entries[0][1])
                    except _LaunchFailed:
                        pass  # speculation is best-effort
        if stage_span is not None:
            # abnormal exits (deadline, retries exceeded, abandonment)
            # leave the stage span open; the coordinator's finalize
            # sweep (end_open_spans) closes it with the query
            stage_span.end()
        return last_handle
