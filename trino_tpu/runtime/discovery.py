"""Node discovery + heartbeat failure detection.

Analogue of DiscoveryNodeManager (main/metadata/DiscoveryNodeManager.java:70
— workers announce, coordinator tracks ACTIVE/SHUTTING_DOWN) and
HeartbeatFailureDetector (main/failuredetector/HeartbeatFailureDetector.java:78
— continuous pings with decay-based failure stats). SURVEY.md §5.3.

Collapsed to the engine's needs: a registry of worker handles, a
background pinger with an exponentially-decayed failure rate, and an
active-set the scheduler consults per scheduling pass (which is how
workers join/leave mid-stream in FTE mode).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class NodeState:
    def __init__(self, handle):
        self.handle = handle
        self.state = "active"  # active | shutting_down | failed
        self.failure_rate = 0.0  # exponentially decayed
        self.last_seen = time.monotonic()


class NodeManager:
    """Tracks workers; the heartbeat loop updates liveness. `handle` is
    anything with .worker_id and .status() (in-process Worker gets a
    trivial status)."""

    DECAY = 0.8  # per-ping decay of the failure rate
    FAIL_THRESHOLD = 0.6

    def __init__(self, ping_interval: float = 1.0):
        self._nodes: Dict[str, NodeState] = {}
        self._lock = threading.Lock()
        self._interval = ping_interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, handle) -> None:
        with self._lock:
            self._nodes[handle.worker_id] = NodeState(handle)

    def active_workers(self) -> List:
        with self._lock:
            return [
                n.handle
                for n in self._nodes.values()
                if n.state == "active"
            ]

    def all_states(self) -> Dict[str, str]:
        with self._lock:
            return {k: n.state for k, n in self._nodes.items()}

    # -- heartbeat loop (HeartbeatFailureDetector.ping:350) --
    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.ping_once()

    def ping_once(self) -> None:
        with self._lock:
            nodes = list(self._nodes.values())
        for n in nodes:
            try:
                status = n.handle.status()
                n.failure_rate *= self.DECAY
                n.last_seen = time.monotonic()
                reported = status.get("state", "active")
                if n.state != "failed" or n.failure_rate < self.FAIL_THRESHOLD:
                    n.state = (
                        "shutting_down"
                        if reported == "shutting_down"
                        else "active"
                    )
            except Exception:
                n.failure_rate = n.failure_rate * self.DECAY + (1 - self.DECAY)
                if n.failure_rate >= self.FAIL_THRESHOLD:
                    n.state = "failed"
