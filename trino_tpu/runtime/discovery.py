"""Node discovery + heartbeat failure detection.

Analogue of DiscoveryNodeManager (main/metadata/DiscoveryNodeManager.java:70
— workers announce, coordinator tracks ACTIVE/SHUTTING_DOWN) and
HeartbeatFailureDetector (main/failuredetector/HeartbeatFailureDetector.java:78
— continuous pings with decay-based failure stats). SURVEY.md §5.3.

Collapsed to the engine's needs: a registry of worker handles, a
background pinger with an exponentially-decayed failure rate, and an
active-set the scheduler consults per scheduling pass (which is how
workers join/leave mid-stream in FTE mode).

Circuit breaking: every node also carries a CircuitBreaker fed by
request outcomes (the NodeManager implements the error-tracker listener
protocol, so HTTP clients and exchange pullers report into it). A node
whose breaker trips is graylisted — `schedulable_workers()` excludes it
so FTE re-placement and new launches avoid the node — while the
heartbeat ping keeps probing it; one successful probe closes the
breaker and returns the node to rotation.

Graceful drain (GracefulShutdownHandler + the SHUTTING_DOWN node state
driven end-to-end): `request_drain` marks a node shutting_down (new
launches stop targeting it immediately) and tells the worker to refuse
task creation; `drain(worker_id, timeout)` additionally waits until
every task on the node reached a terminal state — committed, or failed
and re-placed elsewhere — then marks it `drained` (decommissionable).
Spooled output on a draining node stays readable throughout.
"""

from __future__ import annotations

import threading
from trino_tpu.analysis import threadreg
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import time
from typing import Callable, Dict, List, Optional


class CircuitBreaker:
    """Per-node breaker: `trip_threshold` consecutive failures open it;
    while open the node is graylisted (excluded from scheduling) but
    still probed by the heartbeat loop. After `cooldown_s` the next
    probe half-opens the breaker; a success closes it, another failure
    re-opens it and restarts the cooldown."""

    def __init__(self, trip_threshold: int = 3, cooldown_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_open: Optional[Callable[[], None]] = None):
        self.trip_threshold = trip_threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self.state = "closed"  # closed | open | half_open
        self.consecutive_failures = 0
        self.opened_at: Optional[float] = None
        self.trips = 0  # observability: how often this node graylisted
        # trip listener (replica plane counts breaker_opens through it);
        # fired on closed -> open only, never on half_open re-opens
        self._on_open = on_open

    @property
    def is_open(self) -> bool:
        return self.state in ("open", "half_open")

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.state == "half_open":
            self.state = "open"  # probe failed: restart the cooldown
            self.opened_at = self._clock()
        elif (
            self.state == "closed"
            and self.consecutive_failures >= self.trip_threshold
        ):
            self.state = "open"
            self.opened_at = self._clock()
            self.trips += 1
            if self._on_open is not None:
                try:
                    self._on_open()
                except Exception:
                    pass  # a listener must never mask the trip itself

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state != "closed":
            self.state = "closed"
            self.opened_at = None

    def mark_probing(self) -> None:
        """Transition open -> half_open once the cooldown elapsed (the
        heartbeat calls this right before its probe ping)."""
        if (
            self.state == "open"
            and self._clock() - (self.opened_at or 0.0) >= self.cooldown_s
        ):
            self.state = "half_open"


class NodeState:
    def __init__(self, handle, breaker: Optional[CircuitBreaker] = None):
        self.handle = handle
        # lifecycle: active -> shutting_down (drain requested; running
        # tasks finishing, no new launches) -> drained (nothing left
        # running; the node can be decommissioned). `failed` is the
        # heartbeat detector's verdict and can recover to active.
        self.state = "active"  # active | shutting_down | drained | failed
        self.failure_rate = 0.0  # exponentially decayed
        self.last_seen = time.monotonic()
        self.breaker = breaker or CircuitBreaker()


class NodeManager:
    """Tracks workers; the heartbeat loop updates liveness. `handle` is
    anything with .worker_id and .status() (in-process Worker gets a
    trivial status)."""

    DECAY = 0.8  # per-ping decay of the failure rate
    FAIL_THRESHOLD = 0.6

    def __init__(self, ping_interval: float = 1.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 1.0):
        self._nodes: Dict[str, NodeState] = {}
        self._lock = named_lock("NodeManager._lock")
        self._interval = ping_interval
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown_s = breaker_cooldown_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # state-transition listeners fired from the heartbeat loop:
        # fn(worker_id, old_state, new_state). The membership bridge
        # (runtime/fabric.py MembershipDriver) drives replica
        # join/leave through these.
        self._state_listeners: List[Callable[[str, str, str], None]] = []

    def register(self, handle) -> None:
        with self._lock:
            self._nodes[handle.worker_id] = NodeState(
                handle,
                CircuitBreaker(
                    self._breaker_threshold, self._breaker_cooldown_s
                ),
            )

    def active_workers(self) -> List:
        with self._lock:
            return [
                n.handle
                for n in self._nodes.values()
                if n.state == "active"
            ]

    def schedulable_workers(self) -> List:
        """Active workers whose breaker is closed — the set FTE
        placement and new launches draw from. Graylisted (open/half-
        open) nodes stay out until a heartbeat probe succeeds."""
        with self._lock:
            return [
                n.handle
                for n in self._nodes.values()
                if n.state == "active" and not n.breaker.is_open
            ]

    def all_states(self) -> Dict[str, str]:
        with self._lock:
            return {k: n.state for k, n in self._nodes.items()}

    def breaker_states(self) -> Dict[str, str]:
        with self._lock:
            return {k: n.breaker.state for k, n in self._nodes.items()}

    # -- graceful drain (DiscoveryNodeManager SHUTTING_DOWN end-to-end) --
    def request_drain(self, worker_id: str) -> NodeState:
        """Start draining a worker: mark it SHUTTING_DOWN locally FIRST
        (placement stops targeting it before any network round trip),
        then tell the worker to refuse new launches. An unreachable
        worker still leaves rotation — that is the point of draining."""
        with self._lock:
            n = self._nodes.get(worker_id)
            if n is None:
                raise KeyError(f"unknown worker {worker_id}")
            if n.state not in ("shutting_down", "drained"):
                n.state = "shutting_down"
        try:
            n.handle.shutdown_gracefully()
        except Exception:
            pass
        return n

    def drain(self, worker_id: str, timeout_s: float = 30.0,
              poll_s: float = 0.02) -> bool:
        """Request a drain and wait until every task on the worker
        reached a terminal state (committed, or failed and re-placed by
        the scheduler onto other nodes). Returns True once the node is
        `drained`; False on timeout (the node stays `shutting_down` —
        still out of rotation, still serving its spooled output)."""
        n = self.request_drain(worker_id)
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                st = n.handle.status()
                running = int(st.get("running", st.get("tasks", 0)))
                if running == 0:
                    n.state = "drained"
                    return True
            except Exception:
                pass  # unreachable mid-drain: keep waiting for timeout
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)

    # -- error-tracker listener protocol (destination == worker_id) --
    def report_failure(self, destination: str) -> None:
        with self._lock:
            n = self._nodes.get(destination)
        if n is not None:
            n.breaker.record_failure()

    def report_success(self, destination: str) -> None:
        with self._lock:
            n = self._nodes.get(destination)
        if n is not None:
            n.breaker.record_success()

    # -- heartbeat loop (HeartbeatFailureDetector.ping:350) --
    def start(self) -> None:
        if self._thread is None:
            self._thread = threadreg.spawn(
                "heartbeat-detector", self._loop, owner="HeartbeatFailureDetector"
            )

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.ping_once()

    def add_state_listener(
        self, fn: Callable[[str, str, str], None]
    ) -> None:
        """Register fn(worker_id, old_state, new_state), fired from the
        heartbeat loop on every node state transition. A listener error
        never stalls the ping loop."""
        self._state_listeners.append(fn)

    def _notify_state(self, worker_id: str, old: str, new: str) -> None:
        if old == new:
            return
        for fn in self._state_listeners:
            try:
                fn(worker_id, old, new)
            except Exception:
                pass  # membership bridges must not break failure detection

    def ping_once(self) -> None:
        with self._lock:
            nodes = list(self._nodes.values())
        for n in nodes:
            before = n.state
            n.breaker.mark_probing()
            try:
                status = n.handle.status()
                n.failure_rate *= self.DECAY
                n.last_seen = time.monotonic()
                n.breaker.record_success()
                reported = status.get("state", "active")
                running = int(status.get("running", status.get("tasks", 0)))
                if (
                    reported == "shutting_down"
                    or n.state in ("shutting_down", "drained")
                ):
                    # drain is one-way (locally-requested drains stick
                    # even before the worker acks); shutting_down
                    # settles to drained once nothing is running
                    n.state = "drained" if running == 0 else "shutting_down"
                elif n.state != "failed" or n.failure_rate < self.FAIL_THRESHOLD:
                    n.state = "active"
            except Exception:
                n.failure_rate = n.failure_rate * self.DECAY + (1 - self.DECAY)
                n.breaker.record_failure()
                if n.failure_rate >= self.FAIL_THRESHOLD:
                    n.state = "failed"
            self._notify_state(n.handle.worker_id, before, n.state)
