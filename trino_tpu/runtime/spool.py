"""Spooled (external) exchange: durable task output for fault tolerance.

Analogue of the exchange SPI + filesystem exchange plugin
(spi/exchange/ExchangeManager.java:42, plugin/trino-exchange-filesystem
FileSystemExchangeSink.java:63 — SURVEY.md §2.8, §3.5): each task's
output is persisted per partition and committed atomically, making tasks
idempotent and restartable; consumers read only COMMITTED attempts (the
ExchangeSourceOutputSelector de-duplication of speculative/retried
tasks).

Layout: {base}/{task}/{partition}-{seq}.page + {base}/{task}/committed
(manifest listing page counts per partition, written last).
"""

from __future__ import annotations

import json
import os
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import Dict, List, Optional, Tuple

from trino_tpu.exec.serde import Page, deserialize_page, serialize_page


class SpoolingExchangeSink:
    """OutputBuffer-compatible sink that spools to files
    (SpoolingExchangeOutputBuffer analogue). Same enqueue /
    set_no_more_pages / abort / get_pages surface so
    PartitionedOutputOperator and the results protocol work unchanged —
    get_pages serves from disk after commit (the coordinator's
    deduplicating fetch of the root stage)."""

    def __init__(self, base_dir: str, task_key: str, n_partitions: int):
        self._dir = os.path.join(base_dir, task_key)
        os.makedirs(self._dir, exist_ok=True)
        self._n = n_partitions
        self._seq = [0] * n_partitions
        self._committed = False
        self._aborted = False
        self._lock = named_condition("SpoolingExchangeSink._lock")

    @property
    def n_partitions(self) -> int:
        return self._n

    def enqueue(self, partition: int, page: Page) -> None:
        seq = self._seq[partition]
        self._seq[partition] = seq + 1
        path = os.path.join(self._dir, f"{partition}-{seq}.page")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(serialize_page(page))
        os.replace(tmp, path)

    def set_no_more_pages(self) -> None:
        with self._lock:
            if self._committed or self._aborted:
                return
            manifest = {"pages": list(self._seq)}
            tmp = os.path.join(self._dir, "committed.tmp")
            with open(tmp, "w") as f:
                json.dump(manifest, f)
            os.replace(tmp, os.path.join(self._dir, "committed"))
            self._committed = True
            self._lock.notify_all()

    def abort(self) -> None:
        with self._lock:
            self._aborted = True
            self._lock.notify_all()

    # -- consumer surface (post-commit reads) --
    def get_pages(
        self, partition: int, token: int, max_pages: int = 16, wait: float = 0.0
    ) -> Tuple[List[Page], int, bool]:
        with self._lock:
            if self._aborted:
                raise RuntimeError("spooled output aborted (task failed)")
            if not self._committed:
                if wait > 0:
                    self._lock.wait(timeout=wait)
                if not self._committed:
                    if self._aborted:
                        raise RuntimeError("spooled output aborted (task failed)")
                    return [], token, False
        return read_spool(self._dir, partition, token, max_pages)

    def is_fully_consumed(self) -> bool:
        return self._committed


def read_spool(
    task_dir: str, partition: int, token: int, max_pages: int = 16
) -> Tuple[List[Page], int, bool]:
    """Read a committed task attempt's pages for one partition starting
    at `token` (ExchangeSource analogue; tokens index spooled files, so
    redelivery after a consumer restart is natural)."""
    with open(os.path.join(task_dir, "committed")) as f:
        manifest = json.load(f)
    total = manifest["pages"][partition]
    pages = []
    seq = token
    while seq < total and len(pages) < max_pages:
        with open(os.path.join(task_dir, f"{partition}-{seq}.page"), "rb") as f:
            pages.append(deserialize_page(f.read()))
        seq += 1
    return pages, seq, seq >= total


def spool_fetch(base_dir: str, task_key: str):
    """Location descriptor resolver: ("spool", base_dir, task_key) ->
    fetch callable reading the committed attempt."""
    task_dir = os.path.join(base_dir, task_key)

    def fetch(partition: int, token: int, max_pages: int, wait: float):
        return read_spool(task_dir, partition, token, max_pages)

    return fetch


def is_committed(base_dir: str, task_key: str) -> bool:
    return os.path.exists(os.path.join(base_dir, task_key, "committed"))
