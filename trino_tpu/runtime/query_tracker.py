"""Query deadline hierarchy: the coordinator's time-bounding authority.

Analogue of main/execution/QueryTracker.java (enforceTimeLimits +
failAbandonedQueries — SURVEY.md §runtime): a periodic tick walks every
live query and enforces

  - query_max_planning_time_s   while the query is PLANNING
  - query_max_execution_time_s  while the query is EXECUTING
  - query_max_run_time_s        from submission (QUEUED + PLANNING +
                                EXECUTING — the end-to-end wall bound)
  - query_max_cpu_time_s        aggregated from task-level CPU ledgers
                                (Worker.task_state "cpu_s")

A breached limit kills the query's remote tasks through the registered
kill callback (the DELETE /v1/query/{id} path on HTTP topologies) and
latches a TYPED, NON-RETRYABLE error — EXCEEDED_TIME_LIMIT /
EXCEEDED_CPU_LIMIT are user errors: resubmitting a query that already
spent its budget can only spend it again, so QUERY retry and FTE task
retry must both refuse to replay them. Contrast the worker-side
stuck-task watchdog (runtime/worker.py): a hung split on one node may
well succeed elsewhere, so watchdog interrupts stay RETRYABLE.

The tick is explicit (`tick()`) for deterministic tests and can run on
a background thread (`start()`) for live coordinators, mirroring the
NodeManager's ping_once/start discipline."""

from __future__ import annotations

import dataclasses
import threading
from trino_tpu.analysis import threadreg
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import time
from typing import Callable, Dict, List, Optional, Tuple

# error codes carried INSIDE kill messages so they survive the trip
# through task failure strings and HTTP 500 bodies: any layer can
# re-classify a stringly failure back into the typed error
EXCEEDED_TIME_LIMIT = "EXCEEDED_TIME_LIMIT"
EXCEEDED_CPU_LIMIT = "EXCEEDED_CPU_LIMIT"


class QueryDeadlineError(RuntimeError):
    """A query exceeded one of its time budgets. NON-RETRYABLE by
    design (`retryable = False`): the budget is a property of the query,
    not of the node that ran it."""

    code = EXCEEDED_TIME_LIMIT
    retryable = False


class ExceededTimeLimitError(QueryDeadlineError):
    code = EXCEEDED_TIME_LIMIT


class ExceededCpuLimitError(QueryDeadlineError):
    code = EXCEEDED_CPU_LIMIT


def deadline_code(message: Optional[str]) -> Optional[str]:
    """Extract a deadline error code from a failure message (the
    classification hook for QUERY retry, FTE retry and _raise_if_failed:
    a kill message embeds its code in square brackets)."""
    if not message:
        return None
    for code in (EXCEEDED_TIME_LIMIT, EXCEEDED_CPU_LIMIT):
        if code in message:
            return code
    return None


def deadline_error(message: str) -> QueryDeadlineError:
    """Rehydrate the typed error from a coded failure message."""
    cls = (
        ExceededCpuLimitError
        if deadline_code(message) == EXCEEDED_CPU_LIMIT
        else ExceededTimeLimitError
    )
    return cls(message)


class QueryAbandonedError(RuntimeError):
    """The client stopped polling results; the query is torn down
    instead of computing a result nobody will read. Not a deadline kill
    (no bracketed code) and not retryable — resubmitting an abandoned
    query would just abandon it again."""

    retryable = False


def preemption_check(tracker, base_qid, cancel=None, deadline_epoch_s=None,
                     clock=None):
    """Build the chunk-boundary preemption hook for in-process data
    planes (the mesh chunk loop). The returned callable mirrors what the
    page plane enforces between batches — latched tracker kills, client
    abandonment, the worker-local wall deadline — so a mesh query under
    limits dies with the same typed errors, just at chunk granularity.

    Signature: check(done, total) — the caller's progress through its
    preemption boundaries, embedded in the kill message for
    observability. A checkpoint-resumed run (recovery tier) sets
    `check.resumed_from` — and, after a replica failover, the replica
    that picked the run up via `check.resumed_on` — so a deadline kill
    mid-resume names where the run restarted — the error stays typed
    and non-retryable either way: resuming does not refresh a spent
    budget."""
    import time as _time

    clock = clock or _time.time

    def _resume_ctx() -> str:
        resumed = getattr(check, "resumed_from", None)
        if resumed is None:
            return ""
        replica = getattr(check, "resumed_on", None)
        on = f" on replica {replica}" if replica is not None else ""
        return f" (resumed from chunk {resumed}{on})"

    def _park_ctx() -> str:
        # the scheduler's wait loops update `check.parked_s` while the
        # query sits parked or queued — a deadline firing there names
        # the time spent preempted. Deliberately counted against the
        # budget: parking does not stop a query's wall clock, so a
        # parked query that exceeds its deadline dies typed and never
        # resumes.
        parked = float(getattr(check, "parked_s", 0.0) or 0.0)
        if parked <= 0.0:
            return ""
        return f" (parked {parked:.2f}s)"

    def check(done: int, total: int) -> None:
        # a kill latched by the enforcement tick (planning/run/cpu
        # limits) surfaces here as its typed error — after a checkpoint
        # restore it must still name the resume point, whichever
        # enforcement path landed the kill first
        try:
            tracker.check(base_qid)
        except QueryDeadlineError as e:
            ctx = _resume_ctx() + _park_ctx()
            if not ctx:
                raise
            raise type(e)(
                f"{e} at mesh chunk {done}/{total}{ctx}"
            ) from None
        if cancel is not None and cancel():
            raise QueryAbandonedError(
                f"Query {base_qid} abandoned: client stopped "
                "polling results"
            )
        if deadline_epoch_s is not None and clock() > deadline_epoch_s:
            raise ExceededTimeLimitError(
                "Query exceeded the execution-time limit at mesh chunk "
                f"{done}/{total}{_resume_ctx()}{_park_ctx()} "
                f"[{EXCEEDED_TIME_LIMIT}]"
            )

    check.resumed_from = None
    check.resumed_on = None
    check.parked_s = 0.0
    return check


@dataclasses.dataclass(frozen=True)
class DeadlineLimits:
    """Per-query budgets; 0 (or None) disables a limit."""

    max_planning_time_s: float = 0.0
    max_execution_time_s: float = 0.0
    max_run_time_s: float = 0.0
    max_cpu_time_s: float = 0.0

    @classmethod
    def from_session(cls, session) -> "DeadlineLimits":
        g = lambda n: float(getattr(session, n, 0.0) or 0.0)
        return cls(
            max_planning_time_s=g("query_max_planning_time_s"),
            max_execution_time_s=g("query_max_execution_time_s"),
            max_run_time_s=g("query_max_run_time_s"),
            max_cpu_time_s=g("query_max_cpu_time_s"),
        )

    def any(self) -> bool:
        return any(
            v > 0
            for v in (
                self.max_planning_time_s,
                self.max_execution_time_s,
                self.max_run_time_s,
                self.max_cpu_time_s,
            )
        )


# query lifecycle phases the limits key on
QUEUED = "queued"
PLANNING = "planning"
EXECUTING = "executing"
DONE = "done"


class TrackedQuery:
    def __init__(
        self,
        query_id: str,
        limits: DeadlineLimits,
        kill: Optional[Callable[[str], None]],
        cpu_time_fn: Optional[Callable[[], float]],
        now: float,
    ):
        self.query_id = query_id
        self.limits = limits
        self.kill = kill
        self.cpu_time_fn = cpu_time_fn
        self.created_at = now
        self.phase = QUEUED
        self.planning_started_at: Optional[float] = None
        self.executing_started_at: Optional[float] = None
        self.error: Optional[QueryDeadlineError] = None
        # QUERY retry runs attempts under qN / qNr1 / ... namespaces;
        # the kill must target whichever attempt is live RIGHT NOW
        self.live_query_id = query_id


class QueryTracker:
    """Registry + enforcement tick. `kill` callbacks receive the coded
    kill message; the owner (DistributedQueryRunner / CoordinatorServer)
    routes it to Worker.fail_query / DELETE /v1/query/{id}."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 tick_interval_s: float = 0.05):
        self._clock = clock
        self.tick_interval_s = tick_interval_s
        self._queries: Dict[str, TrackedQuery] = {}
        self._lock = named_lock("QueryTracker._lock")
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # observability: (query_id, code, message) per enforcement kill
        self.kills: List[Tuple[str, str, str]] = []

    # -- registry --
    def register(
        self,
        query_id: str,
        limits: DeadlineLimits,
        kill: Optional[Callable[[str], None]] = None,
        cpu_time_fn: Optional[Callable[[], float]] = None,
        phase: str = QUEUED,
    ) -> TrackedQuery:
        now = self._clock()
        tq = TrackedQuery(query_id, limits, kill, cpu_time_fn, now)
        with self._lock:
            self._queries[query_id] = tq
        if phase != QUEUED:
            self.transition(query_id, phase)
        return tq

    def transition(self, query_id: str, phase: str) -> None:
        tq = self._queries.get(query_id)
        if tq is None:
            return
        now = self._clock()
        tq.phase = phase
        if phase == PLANNING and tq.planning_started_at is None:
            tq.planning_started_at = now
        if phase == EXECUTING and tq.executing_started_at is None:
            tq.executing_started_at = now

    def set_live_query_id(self, query_id: str, live: str) -> None:
        tq = self._queries.get(query_id)
        if tq is not None:
            tq.live_query_id = live

    def complete(self, query_id: str) -> None:
        with self._lock:
            self._queries.pop(query_id, None)

    def check(self, query_id: str) -> None:
        """Raise the query's latched deadline error, if any — the
        synchronous surface for phases with no tasks to kill (queued,
        planning, between retry attempts)."""
        tq = self._queries.get(query_id)
        if tq is not None and tq.error is not None:
            raise tq.error

    def enforce_now(self, query_id: str) -> None:
        """One synchronous enforcement sweep for one query. Phase
        boundaries call this so a budget blown inside a sub-tick phase
        (planning that finishes before the first background tick fires)
        still latches its typed kill — identical to a tick landing at
        this instant."""
        with self._lock:
            tq = self._queries.get(query_id)
            if tq is None or tq.error is not None or tq.phase == DONE:
                return
        err = self._enforce(tq, self._clock())
        if err is None:
            return
        tq.error = err
        self.kills.append((tq.query_id, err.code, str(err)))
        if tq.kill is not None:
            try:
                tq.kill(str(err))
            except Exception:
                pass  # the latched error still fails the query

    # -- enforcement --
    def _enforce(self, tq: TrackedQuery, now: float) -> Optional[QueryDeadlineError]:
        lim = tq.limits
        if lim.max_run_time_s > 0 and now - tq.created_at > lim.max_run_time_s:
            return ExceededTimeLimitError(
                f"Query {tq.query_id} exceeded the maximum run time limit "
                f"of {lim.max_run_time_s}s [{EXCEEDED_TIME_LIMIT}]"
            )
        if (
            tq.phase == PLANNING
            and lim.max_planning_time_s > 0
            and tq.planning_started_at is not None
            and now - tq.planning_started_at > lim.max_planning_time_s
        ):
            return ExceededTimeLimitError(
                f"Query {tq.query_id} exceeded the maximum planning time "
                f"limit of {lim.max_planning_time_s}s [{EXCEEDED_TIME_LIMIT}]"
            )
        if (
            tq.phase == EXECUTING
            and lim.max_execution_time_s > 0
            and tq.executing_started_at is not None
            and now - tq.executing_started_at > lim.max_execution_time_s
        ):
            return ExceededTimeLimitError(
                f"Query {tq.query_id} exceeded the maximum execution time "
                f"limit of {lim.max_execution_time_s}s [{EXCEEDED_TIME_LIMIT}]"
            )
        if lim.max_cpu_time_s > 0 and tq.cpu_time_fn is not None:
            try:
                cpu = tq.cpu_time_fn()
            except Exception:
                cpu = 0.0
            if cpu > lim.max_cpu_time_s:
                return ExceededCpuLimitError(
                    f"Query {tq.query_id} exceeded the CPU time limit of "
                    f"{lim.max_cpu_time_s}s (used {cpu:.3f}s) "
                    f"[{EXCEEDED_CPU_LIMIT}]"
                )
        return None

    def tick(self, now: Optional[float] = None) -> List[Tuple[str, str]]:
        """One enforcement sweep; returns [(query_id, code)] for every
        kill issued this tick. A query already carrying an error is not
        re-killed (the kill latches)."""
        now = self._clock() if now is None else now
        t_tick = time.monotonic()
        with self._lock:
            live = [
                tq for tq in self._queries.values()
                if tq.error is None and tq.phase != DONE
            ]
        fired: List[Tuple[str, str]] = []
        for tq in live:
            err = self._enforce(tq, now)
            if err is None:
                continue
            tq.error = err
            self.kills.append((tq.query_id, err.code, str(err)))
            fired.append((tq.query_id, err.code))
            if tq.kill is not None:
                try:
                    tq.kill(str(err))
                except Exception:
                    pass  # the latched error still fails the query
        from trino_tpu.runtime.metrics import METRICS

        METRICS.observe("tracker_tick_s", time.monotonic() - t_tick)
        return fired

    # -- background tick loop (live coordinators) --
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.tick_interval_s):
                self.tick()

        self._thread = threadreg.spawn(
            "query-tracker", loop, owner="QueryTracker"
        )

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(1.0)
            self._thread = None
