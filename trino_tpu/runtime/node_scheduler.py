"""Node selection + memory-aware task placement.

Analogues of the reference's scheduling policies (SURVEY.md §2.3):

- `UniformNodeSelector` — least-loaded placement with a per-node task
  cap and optional locality preference
  (main/execution/scheduler/NodeScheduler.java:54,
  UniformNodeSelector.java:67 — maxSplitsPerNode / preferred-host
  selection, with tasks as this engine's scheduling unit).
- `PartitionMemoryEstimator` — per-fragment task-memory estimates that
  GROW after memory failures, so retries re-place onto roomier nodes
  (ExponentialGrowthPartitionMemoryEstimator).
- `BinPackingNodeAllocator` — fits estimated task memory into per-node
  budgets, choosing the node with the most free room
  (BinPackingNodeAllocatorService.java:82). When nothing fits it falls
  back to the emptiest node rather than queueing — this engine's
  workers spill under pressure, so over-admission degrades instead of
  OOM-killing.
"""

from __future__ import annotations

import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import Dict, List, Optional, Sequence


def _drop_graylisted(active: Sequence, node_manager) -> List:
    """Filter out workers whose circuit breaker is open (graylist,
    runtime/discovery.py). All-gray degrades to the full set — placing
    on a suspect node beats starving the query."""
    if node_manager is None:
        return list(active)
    try:
        ok = {id(h) for h in node_manager.schedulable_workers()}
    except Exception:
        return list(active)
    filtered = [h for h in active if id(h) in ok]
    return filtered or list(active)


class UniformNodeSelector:
    """Pick the active node with the fewest running tasks; nodes at the
    cap are skipped (all-at-cap falls back to global least-loaded, the
    reference's best-effort under full cluster). With a `node_manager`,
    graylisted (open-breaker) nodes are excluded from every tier."""

    def __init__(self, max_tasks_per_node: Optional[int] = None,
                 node_manager=None):
        self.max_tasks_per_node = max_tasks_per_node
        self.node_manager = node_manager
        # local assignment ledger: placements increment locally; each
        # handle's remote status() is probed ONCE (its pre-existing
        # load), not per placement — a slow worker must not serialize
        # every launch behind an HTTP round trip
        self._assigned: Dict[int, int] = {}
        self._baseline: Dict[int, int] = {}
        self._lock = named_lock("UniformNodeSelector._lock")

    def _load(self, handle) -> int:
        key = id(handle)
        if key not in self._baseline:
            try:
                self._baseline[key] = int(handle.status().get("tasks", 0))
            except Exception:
                self._baseline[key] = 0
        return self._baseline[key] + self._assigned.get(key, 0)

    def _pick_below_cap_locked(self, pool: Sequence):
        """Least-loaded node of `pool` under the cap, or None (caller
        holds the lock)."""
        loads = [(self._load(h), i, h) for i, h in enumerate(pool)]
        loads.sort(key=lambda t: (t[0], t[1]))
        for load, _, h in loads:
            if (
                self.max_tasks_per_node is None
                or load < self.max_tasks_per_node
            ):
                self._assigned[id(h)] = self._assigned.get(id(h), 0) + 1
                return h
        return None

    def select(self, active: Sequence, preferred: Sequence = ()) -> object:
        if not active:
            raise RuntimeError("no active workers")
        active = _drop_graylisted(active, self.node_manager)
        preferred = [h for h in preferred if h in active]
        with self._lock:
            for pool in (list(preferred), list(active)):
                if not pool:
                    continue
                pick = self._pick_below_cap_locked(pool)
                if pick is not None:
                    return pick
            # every node at cap: least-loaded overall
            _, _, h = min(
                ((self._load(h), i, h) for i, h in enumerate(active)),
                key=lambda t: (t[0], t[1]),
            )
            self._assigned[id(h)] = self._assigned.get(id(h), 0) + 1
            return h

    def release(self, handle) -> None:
        with self._lock:
            n = self._assigned.get(id(handle), 0)
            if n > 1:
                self._assigned[id(handle)] = n - 1
            else:
                self._assigned.pop(id(handle), None)


class TopologyAwareNodeSelector(UniformNodeSelector):
    """Locality-tiered placement (TopologyAwareNodeSelector.java /
    FlatNetworkTopology): a split carrying a preferred LOCATION fills
    nodes tier by tier — same host, then same rack/pod (the ICI-island
    analogue on a TPU pod: co-scheduling a fragment's tasks inside one
    island keeps its exchanges on ICI instead of DCN), then anywhere.
    Node locations are "host" or "rack/host" strings; each tier re-uses
    the least-loaded policy of the parent class."""

    def __init__(self, locations: Dict[int, str],
                 max_tasks_per_node: Optional[int] = None,
                 node_manager=None):
        super().__init__(max_tasks_per_node, node_manager=node_manager)
        # id(handle) -> "rack/host" (or bare "host")
        self._locations = dict(locations)

    @staticmethod
    def _rack(loc: str) -> str:
        return loc.rsplit("/", 1)[0] if "/" in loc else loc

    def select(self, active: Sequence, preferred: Sequence = (),
               location: Optional[str] = None) -> object:
        if location is None:
            return super().select(active, preferred)
        active = _drop_graylisted(active, self.node_manager)
        same_host = [
            h for h in active
            if self._locations.get(id(h)) == location
        ]
        want_rack = self._rack(location)
        same_rack = [
            h for h in active
            if self._rack(self._locations.get(id(h), "")) == want_rack
        ]
        # STRICT tiers: a below-cap same-host node beats ANY same-rack
        # node regardless of load; each tier is least-loaded internally
        with self._lock:
            for pool in (same_host, same_rack, list(preferred)):
                if not pool:
                    continue
                pick = self._pick_below_cap_locked(pool)
                if pick is not None:
                    return pick
        return super().select(active)


class PartitionMemoryEstimator:
    """Per-fragment estimated task memory; doubles after each
    memory-classed failure (the reference's exponential growth)."""

    GROWTH = 2.0

    def __init__(self, default_bytes: int = 64 << 20):
        self.default_bytes = default_bytes
        self._est: Dict[int, float] = {}

    def estimate(self, fragment_id: int) -> int:
        return int(self._est.get(fragment_id, self.default_bytes))

    def register_failure(self, fragment_id: int, failure: Optional[str]) -> None:
        text = (failure or "").lower()
        if "memory" in text or "oom" in text:
            cur = self._est.get(fragment_id, self.default_bytes)
            self._est[fragment_id] = cur * self.GROWTH


class BinPackingNodeAllocator:
    """Track estimated bytes outstanding per node; place each task on
    the node with the most free budget that fits."""

    DEFAULT_NODE_BYTES = 1 << 30

    def __init__(self, capacity_fn=None, node_manager=None):
        """capacity_fn(handle) -> node budget in bytes (defaults to the
        handle's memory pool size, else DEFAULT_NODE_BYTES). With a
        `node_manager`, graylisted nodes are excluded from packing."""
        self._capacity_fn = capacity_fn or self._default_capacity
        self.node_manager = node_manager
        self._used: Dict[int, float] = {}
        self._lock = named_lock("BinPackingNodeAllocator._lock")

    @staticmethod
    def _default_capacity(handle) -> int:
        pool = getattr(handle, "memory_pool", None)
        total = getattr(pool, "total_bytes", None)
        return int(total) if total else BinPackingNodeAllocator.DEFAULT_NODE_BYTES

    def free_bytes(self, handle) -> float:
        return self._capacity_fn(handle) - self._used.get(id(handle), 0.0)

    def acquire(
        self, active: Sequence, estimated_bytes: int,
        avoid: Optional[object] = None,
    ) -> object:
        active = _drop_graylisted(active, self.node_manager)
        candidates = [h for h in active if h is not avoid] or list(active)
        if not candidates:
            raise RuntimeError("no active workers")
        with self._lock:
            fitting = [
                h for h in candidates
                if self.free_bytes(h) >= estimated_bytes
            ]
            pool = fitting or candidates  # over-admit rather than starve
            best = max(
                range(len(pool)), key=lambda i: self.free_bytes(pool[i])
            )
            h = pool[best]
            self._used[id(h)] = self._used.get(id(h), 0.0) + estimated_bytes
            return h

    def release(self, handle, estimated_bytes: int) -> None:
        with self._lock:
            left = self._used.get(id(handle), 0.0) - estimated_bytes
            if left > 0:
                self._used[id(handle)] = left
            else:
                self._used.pop(id(handle), None)
