"""QueryInfo/TaskInfo aggregation: Driver -> Task -> Stage -> Query.

Analogue of the reference's QueryInfo/StageInfo/TaskInfo JSON tree
(QueryResource GET /v1/query/{id}; StageStateMachine rolling operator
summaries up from task status — SURVEY.md §5.1). Workers report raw
per-pipeline OperatorStats dicts in task status; this module merges them
positionally per stage (same fragment -> same operator layout), attaches
per-stage expected-vs-observed lowering counts from the census ledger,
and flattens everything into one plain-data dict the server can serve
and EXPLAIN ANALYZE can render. Shared by the coordinator's pipelined
and FTE paths so the two cannot drift apart."""

from __future__ import annotations

from typing import Dict, List, Optional


def merge_operator_stats(
    per_task: List[List[List[dict]]],
) -> List[List[dict]]:
    """Sum numeric OperatorStats fields positionally across a stage's
    tasks: every task of a fragment runs the same pipeline layout, so
    (pipeline index, operator index) identifies the same operator.
    Non-numeric fields (operator name, device_synced bool) keep the
    first task's value; device_synced ORs so one synced task marks the
    merged line."""
    merged: List[List[dict]] = []
    for groups in per_task:
        if groups is None:
            continue
        for pi, group in enumerate(groups):
            while len(merged) <= pi:
                merged.append([])
            for oi, op in enumerate(group):
                if oi >= len(merged[pi]):
                    merged[pi].append(dict(op))
                else:
                    acc = merged[pi][oi]
                    for k, v in op.items():
                        if isinstance(v, bool):
                            acc[k] = bool(acc.get(k)) or v
                        elif isinstance(v, (int, float)):
                            acc[k] = acc.get(k, 0) + v
    return merged


def build_task_info(task_id: str, state: dict) -> dict:
    """One task attempt's TaskInfo from its worker status dict."""
    start = state.get("start_time")
    end = state.get("end_time")
    wall = (end - start) if (start is not None and end is not None) else None
    return {
        "task_id": task_id,
        "state": state.get("state"),
        "failure": state.get("failure"),
        "cpu_s": float(state.get("cpu_s") or 0.0),
        "wall_s": wall,
        "operator_stats": state.get("stats"),
        "shape_classes": int(state.get("shape_classes") or 0),
    }


def build_stage_info(
    fragment_id: int,
    task_infos: List[dict],
    expected_lowerings: Optional[int] = None,
) -> dict:
    """Stage rollup: merged operator lines + totals over the stage's
    task attempts. `expected_lowerings` is the static census prediction
    for this fragment (sql/validate.py shape_census); observed is the
    max per-task ledger count — every task of a fragment compiles the
    same classes, so summing would overcount by the task count."""
    merged = merge_operator_stats(
        [t.get("operator_stats") for t in task_infos]
    )
    flat = [op for group in merged for op in group]
    info = {
        "fragment_id": fragment_id,
        "tasks": len(task_infos),
        "task_infos": task_infos,
        "operator_summaries": merged,
        "cpu_s": sum(t["cpu_s"] for t in task_infos),
        "wall_s": max(
            (t["wall_s"] for t in task_infos if t["wall_s"] is not None),
            default=None,
        ),
        "input_rows": sum(int(op.get("input_rows") or 0) for op in flat),
        "output_rows": sum(int(op.get("output_rows") or 0) for op in flat),
        "device_synced": any(bool(op.get("device_synced")) for op in flat),
        "observed_lowerings": max(
            (t["shape_classes"] for t in task_infos), default=0
        ),
    }
    if expected_lowerings is not None:
        info["expected_lowerings"] = int(expected_lowerings)
    return info


def build_query_info(
    query_id: str,
    state: str,
    sql: str = "",
    wall_s: float = 0.0,
    stages: Optional[List[dict]] = None,
    peak_memory_bytes: int = 0,
    compile_count: int = 0,
    counters: Optional[Dict[str, float]] = None,
    error_code: Optional[str] = None,
    failure: Optional[str] = None,
    retry_count: int = 0,
    attempt_count: int = 1,
    data_plane: str = "http",
    mesh_fallback: Optional[str] = None,
) -> dict:
    """The final QueryInfo document. Counters are the engine-counter
    deltas (rows_scanned/bytes_scanned/rows_shuffled/...) attributed to
    this query; peak memory is the sum of per-worker pool watermarks —
    an upper bound on any instant's cluster-wide total, exact when one
    worker dominates."""
    stages = stages or []
    return {
        "query_id": query_id,
        "state": state,
        "sql": sql,
        "wall_s": wall_s,
        "cpu_s": sum(s.get("cpu_s") or 0.0 for s in stages),
        "peak_memory_bytes": int(peak_memory_bytes),
        "compile_count": int(compile_count),
        "counters": dict(counters or {}),
        "error_code": error_code,
        "failure": failure,
        "retry_count": int(retry_count),
        "attempt_count": int(attempt_count),
        "data_plane": data_plane,
        # why the query left the mesh plane (None = it ran there, or
        # never would have — read together with data_plane)
        "mesh_fallback": mesh_fallback,
        "stages": stages,
    }


def stage_text(stage: dict) -> str:
    """EXPLAIN ANALYZE rendering of one stage's rollup: the merged
    operator lines through the shared OperatorStats formatter (so local
    and distributed output cannot drift apart), then one summary line
    per task attempt — the per-worker detail the merged lines lose."""
    from trino_tpu.exec.stats import OperatorStats, render_stats

    groups = [
        [OperatorStats(**{k: v for k, v in op.items()
                          if k in OperatorStats.__dataclass_fields__})
         for op in group]
        for group in stage["operator_summaries"]
    ]
    lines = [
        f"\nFragment {stage['fragment_id']} [{stage['tasks']} tasks]:",
        render_stats(groups),
    ]
    if stage.get("expected_lowerings") is not None:
        lines.append(
            f"lowerings: expected={stage['expected_lowerings']} "
            f"observed={stage['observed_lowerings']}"
        )
    if stage.get("estimated_vs_observed"):
        lines.append(stage["estimated_vs_observed"])
    for t in stage["task_infos"]:
        wall = t.get("wall_s")
        wall_txt = f"{wall * 1000:.1f}ms" if wall is not None else "?"
        rows = 0
        for group in t.get("operator_stats") or []:
            for op in group:
                rows = max(rows, int(op.get("output_rows") or 0))
        lines.append(
            f"  task {t['task_id']}: {t.get('state')} "
            f"wall={wall_txt} cpu={t['cpu_s'] * 1000:.1f}ms "
            f"peak_rows={rows}"
        )
    return "\n".join(lines)
