"""Distributed runtime: workers, tasks, buffers, exchange, scheduling.

The coordinator/worker split of the reference (SURVEY.md §1 layers 2–9)
— a Python/host control plane around the XLA device data plane. The
in-process form (threads standing in for worker hosts) is the tier-3
DistributedQueryRunner test topology; the HTTP form runs the same task
runtime behind a real wire.
"""

from trino_tpu.runtime.buffers import OutputBuffer
from trino_tpu.runtime.coordinator import DistributedQueryRunner
from trino_tpu.runtime.worker import Worker

__all__ = ["OutputBuffer", "DistributedQueryRunner", "Worker"]
