"""Worker-side task runtime: plan a fragment, run its drivers, feed the
output buffer.

Analogue of main/execution/SqlTask / SqlTaskExecution.java:84 (drivers
from DriverFactories per split/task lifecycle) + SqlTaskManager.updateTask
(SqlTaskManager.java:466 — LocalExecutionPlanner.plan at task creation,
:520). TPU-first delta: one thread per task runs its pipelines in
dependency order (build sides before probes); blocking on exchange input
and buffer backpressure happens inside operators, so Trino's 1-second
cooperative quanta are unnecessary — device kernels are the quanta.
"""

from __future__ import annotations

import dataclasses
import threading
from trino_tpu.analysis import threadreg
import traceback
from typing import Callable, Dict, List, Optional, Tuple

from trino_tpu.exec.driver import Driver, Pipeline
from trino_tpu.exec.exchange_ops import PartitionedOutputOperator
from trino_tpu.runtime.buffers import OutputBuffer
from trino_tpu.runtime.exchange import DirectExchangeClient, ExchangeLocation
from trino_tpu.sql.fragmenter import PlanFragment
from trino_tpu.sql.local_planner import LocalPlanner, Schema


@dataclasses.dataclass(frozen=True)
class TaskId:
    query_id: str
    fragment_id: int
    partition: int
    attempt: int = 0  # FTE retries re-run a partition as attempt+1

    def __str__(self) -> str:
        base = f"{self.query_id}.{self.fragment_id}.{self.partition}"
        return f"{base}.a{self.attempt}" if self.attempt else base


@dataclasses.dataclass
class TaskSpec:
    """Everything a worker needs to run one task (TaskUpdateRequest
    analogue: fragment + splits + output buffer layout + input
    locations). `input_locations` maps producer fragment id -> list of
    fetch callables (one per producer task)."""

    task_id: TaskId
    fragment: PlanFragment
    n_output_partitions: int
    remote_schemas: Dict[int, Schema]
    scan_slice: Optional[Tuple[int, int]]  # (task_index, task_count)
    input_locations: Dict[int, List[Callable]]  # fid -> [fetch]
    batch_rows: int = 1 << 20
    target_splits: int = 1
    # FTE: spool output to this directory instead of a live buffer
    # (SpoolingExchangeOutputBuffer path, SURVEY.md §3.5)
    spool_dir: Optional[str] = None
    dynamic_filtering: bool = True
    # EXPLAIN ANALYZE: wrap operators with timing/row instrumentation
    # and report OperatorStats in task status (TaskInfo.getStats path).
    # Off by default — row counting forces a per-batch device sync.
    collect_stats: bool = False
    # intra-task pipeline parallelism (LocalExchange): run hash-build
    # pipelines concurrently and overlap remote-page pulls with the
    # compute chain (task.concurrency analogue)
    task_concurrency: int = 2
    # compile regime: pad operator-facing batches onto the session's
    # capacity ladder so FTE re-attempts re-land on already-compiled
    # (operator, capacity, dtype) lowerings (compile/shapes.py)
    shape_stabilization: bool = True
    capacity_ladder_base: int = 2
    # query tracing (runtime/tracing.py wire_context dict): when set the
    # task records one operator span per operator, parented on the
    # coordinator's task-attempt span, shipped back in terminal status
    trace_ctx: Optional[dict] = None
    # worker-LOCAL deadline (wall-clock epoch seconds, so the value
    # survives crossing a process boundary): the driver checks it at
    # every batch boundary and fails the task itself instead of waiting
    # for the coordinator's enforcement tick to reach across the wire.
    # Carries the EXCEEDED_TIME_LIMIT code so the coordinator re-types
    # the travelled string as non-retryable. None = no local deadline.
    deadline_epoch_s: Optional[float] = None
    # recovery tier (trino_tpu/recovery/): tee this task's wire pages
    # into the stage-output recorder so QUERY retry can substitute the
    # fragment's completed output instead of recomputing it
    record_output: bool = False


def _resolve_fetch(location):
    """An input location is either a direct fetch callable (in-process
    topology) or a descriptor — ("http", uri, task_id) for live pull
    between processes, ("spool", base_dir, task_key) for a committed
    FTE attempt — the wire forms a codec-encoded TaskSpec carries."""
    if callable(location):
        return location
    kind, a, b = location
    if kind == "http":
        from trino_tpu.runtime.http import http_fetch

        return http_fetch(a, b)
    assert kind == "spool", kind
    from trino_tpu.runtime.spool import spool_fetch

    return spool_fetch(a, b)


class _MidFailureBuffer:
    """Buffer proxy that lets the FailureInjector kill a task AFTER it
    produced output (the partially-spooled retry path of
    BaseFailureRecoveryTest)."""

    def __init__(self, inner, injector, task_id):
        self._inner = inner
        self._injector = injector
        self._task_id = task_id
        self._produced = False

    def enqueue(self, partition, page):
        self._inner.enqueue(partition, page)
        if not self._produced:
            self._produced = True
            self._injector.check(self._task_id, "mid")

    def set_no_more_pages(self):
        self._inner.set_no_more_pages()


class TaskExecution:
    """One running task: plans the fragment, runs drivers on a thread,
    exposes its OutputBuffer for consumers (TaskStateMachine states
    collapsed to PLANNED/RUNNING/FINISHED/FAILED)."""

    def __init__(self, spec: TaskSpec, catalogs, failure_injector=None,
                 memory_pool=None):
        self.spec = spec
        if spec.spool_dir is not None:
            from trino_tpu.runtime.spool import SpoolingExchangeSink

            self.buffer = SpoolingExchangeSink(
                spec.spool_dir, str(spec.task_id), spec.n_output_partitions
            )
        else:
            self.buffer = OutputBuffer(spec.n_output_partitions)
        # listener-driven lifecycle (TaskStateMachine analogue,
        # runtime/state_machine.py); `.state` stays the string API the
        # worker/coordinator protocol reads
        from trino_tpu.runtime.state_machine import task_state_machine

        self._state_machine = task_state_machine(str(spec.task_id))
        self.failure: Optional[str] = None
        self._clients: List[DirectExchangeClient] = []
        self._catalogs = catalogs
        self._injector = failure_injector
        self._memory_pool = memory_pool
        self._thread: Optional[threading.Thread] = None
        self._stat_groups = None  # [[OperatorStats]] when collect_stats
        # stuck-task watchdog surface: drivers heartbeat per batch
        # (Driver observer -> _on_batch); the watchdog compares
        # last_progress_at against stuck_task_interrupt_s and the
        # diagnostic names current_operator
        self.last_progress_at: Optional[float] = None
        self.current_operator: Optional[str] = None
        # per-task CPU ledger (thread CPU seconds across this task's
        # driver threads) — the coordinator QueryTracker aggregates
        # these into the query_max_cpu_time_s budget
        self._cpu_base: Dict[int, float] = {}
        self._cpu_by_thread: Dict[int, float] = {}
        # True once every shape class the census predicts for this
        # fragment is warm (warmup compile, or a prior completed run) —
        # the worker watchdog may then apply the tighter
        # stuck_task_interrupt_warm_s threshold: no first-batch compile
        # stall is possible, so silence means genuinely stuck
        self.shapes_warm: bool = False
        self._census_keys: frozenset = frozenset()
        # observability: wall-clock bounds for TaskInfo; observed shape
        # classes (expected-vs-observed lowerings per stage); the remote
        # span recorder + wrapped operators when tracing is on
        self.start_time: Optional[float] = None
        self.end_time: Optional[float] = None
        self._shape_ledger: set = set()
        self._trace = None
        self._instrumented: list = []

    def operator_stats(self):
        """JSON-ready [[dict]] per pipeline, or None."""
        import dataclasses as _dc

        if self._stat_groups is None:
            return None
        if self.state != "running":
            # terminal: resolve deferred row counts so the final
            # TaskInfo carries exact numbers even on the failure path,
            # where the success-path close_span sweep never ran
            for op in self._instrumented:
                op.flush_counts()
        return [
            [_dc.asdict(s) for s in group] for group in self._stat_groups
        ]

    def trace_spans(self):
        """Exported operator span dicts (None when tracing is off).
        The worker ships these only for TERMINAL tasks so the
        coordinator never grafts a still-open span."""
        if self._trace is None:
            return None
        return self._trace.export()["spans"]

    def observed_shape_classes(self) -> int:
        return len(self._shape_ledger)

    def expected_shape_classes(self) -> int:
        return len(self._census_keys)

    def heartbeat(self) -> None:
        """Operator-internal liveness beat (InstrumentedOperator fires
        this at entry AND exit of every add_input/get_output/finish):
        refreshes watchdog freshness at tens-of-ms granularity without
        naming an operator, so it never ARMS the watchdog — arming
        still requires a completed batch (_on_batch)."""
        import time

        self.last_progress_at = time.monotonic()

    @property
    def state(self) -> str:
        return self._state_machine.get()

    @state.setter
    def state(self, value: str) -> None:
        self._state_machine.set(value)

    def add_state_listener(self, fn) -> None:
        self._state_machine.add_listener(fn)

    # -- lifecycle --
    def start(self) -> None:
        self.state = "running"
        self._thread = threadreg.spawn(
            str(self.spec.task_id), self._run, owner="TaskExecution"
        )

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def abort(self) -> None:
        # terminal states latch: aborting an already-finished/failed
        # task keeps its verdict (TaskStateMachine.abort contract)
        self._state_machine.set("aborted")
        self.buffer.abort()
        for c in self._clients:
            c.close()

    # -- progress / CPU accounting (watchdog + deadline surfaces) --
    def _stopping(self) -> bool:
        return self.state in ("aborted", "failed")

    def _on_batch(self, op_name: str, moved: bool) -> None:
        """Driver observer: refresh the heartbeat and the CPU ledger.
        `moved=False` marks a blocked wait (starved on input — upstream's
        watchdog problem, not ours), which refreshes freshness without
        consulting the injector's "batch" site."""
        import time

        if moved:
            # only a COMPLETED batch arms the watchdog and names the
            # operator; a blocked wait refreshes freshness but proves
            # nothing about this task's own progress
            self.current_operator = op_name
        self.last_progress_at = time.monotonic()
        tid = threading.get_ident()
        ct = time.thread_time()
        base = self._cpu_base.setdefault(tid, ct)
        self._cpu_by_thread[tid] = ct - base
        deadline = self.spec.deadline_epoch_s
        if deadline is not None and time.time() > deadline:
            # worker-local enforcement: kill between batches without a
            # coordinator round trip; fail() is idempotent on terminal
            # states so racing the coordinator's own kill is safe
            from trino_tpu.runtime.query_tracker import (
                EXCEEDED_TIME_LIMIT,
            )

            self.fail(
                f"Task {self.spec.task_id}: worker-local deadline "
                f"passed ({time.time() - deadline:.3f}s over) "
                f"[{EXCEEDED_TIME_LIMIT}]"
            )
            return
        if moved and self._injector is not None:
            # the hung-operator chaos site: a stall here models an
            # operator wedged mid-batch; abort-polling lets a
            # watchdog-failed task wake and unwind promptly
            self._injector.check(
                self.spec.task_id, "batch", abort=self._stopping
            )

    def cpu_time_s(self) -> float:
        return sum(self._cpu_by_thread.values())

    def interrupt_if_stuck(
        self, timeout_s: float, now: Optional[float] = None
    ) -> Optional[str]:
        """Watchdog entry: if this RUNNING task has made no batch
        progress for longer than timeout_s, fail it with a diagnostic
        naming the stuck operator and its last batch timestamp, and
        return the diagnostic. The failure carries NO deadline code —
        stuck-task interrupts are RETRYABLE (a hung split on this worker
        may succeed elsewhere), unlike QueryTracker deadline kills.

        The watchdog arms at the FIRST batch boundary: startup work
        before any batch (XLA compilation, cold split materialization,
        connector data generation) is legitimate unbounded compute the
        batch-granularity heartbeat cannot see inside, so killing on it
        would interrupt healthy tasks — and each retry would re-block on
        the same warm-up and die the same way. "No progress" means "was
        progressing, then stopped"; a task wedged before its first batch
        is the coordinator deadline hierarchy's kill, not ours."""
        import time

        if self.state != "running" or self.last_progress_at is None:
            return None
        if self.current_operator is None:
            return None  # still in startup: not yet armed
        now = time.monotonic() if now is None else now
        age = now - self.last_progress_at
        if age <= timeout_s:
            return None
        diag = (
            f"Stuck task {self.spec.task_id}: no progress for {age:.3f}s "
            f"(stuck_task_interrupt_s={timeout_s}) in operator "
            f"{self.current_operator or 'task startup'}; last batch at "
            f"t={self.last_progress_at:.3f}"
        )
        self.fail(diag)
        return diag

    def fail(self, message: str) -> None:
        """External kill (low-memory killer, DELETE /v1/query,
        speculation-loser cancellation): latch a FAILED verdict carrying
        `message`, then abort the buffer and exchange clients so the
        task's driver stops cooperatively at its next batch boundary.
        Terminal tasks keep their existing verdict."""
        if self.state in ("finished", "failed", "aborted"):
            return
        self.failure = message
        self.state = "failed"
        self.abort()

    # -- execution --
    def _injected_fetch(self, fetch):
        """Chaos hook: the injector is consulted per exchange fetch (the
        "fetch" site) so fault schedules can drop a bounded number of
        page pulls — absorbed by the exchange client's retry loop."""

        def wrapped(partition, token, max_pages, wait):
            self._injector.check(self.spec.task_id, "fetch")
            return fetch(partition, token, max_pages, wait)

        return wrapped

    def _make_remote_source(self, fragment_ids) -> DirectExchangeClient:
        locations = []
        my_partition = self.spec.task_id.partition
        for fid in fragment_ids:
            for i, loc in enumerate(self.spec.input_locations.get(fid, [])):
                fetch = _resolve_fetch(loc)
                if self._injector is not None:
                    fetch = self._injected_fetch(fetch)
                dest = (
                    f"{loc[0]}:{loc[1]}" if isinstance(loc, tuple)
                    else f"local:f{fid}.{i}"
                )
                locations.append(
                    ExchangeLocation(fetch, my_partition, destination=dest)
                )
        client = DirectExchangeClient(locations)
        self._clients.append(client)
        return client

    def _run(self) -> None:
        import time

        spec = self.spec
        ctx: dict = {
            "make_remote_source": self._make_remote_source,
            "query_id": spec.task_id.query_id,
        }
        # heartbeat starts at task start, not first batch: a task hung
        # before producing anything is still watchdog-visible
        self.last_progress_at = time.monotonic()
        self.start_time = time.time()
        if spec.trace_ctx is not None:
            from trino_tpu.runtime.tracing import QueryTrace

            self._trace = QueryTrace.remote(
                spec.trace_ctx, query_id=spec.task_id.query_id
            )
        from trino_tpu.runtime.metrics import set_compile_attribution

        prev_attr = set_compile_attribution(spec.task_id.query_id)
        try:
            if self._injector is not None:
                self._injector.check(spec.task_id, "start")
            stabilizer = None
            if spec.shape_stabilization:
                from trino_tpu.compile.shapes import (
                    CapacityLadder,
                    ShapeStabilizer,
                )

                stabilizer = ShapeStabilizer(
                    CapacityLadder(base=spec.capacity_ladder_base),
                    batch_rows=spec.batch_rows,
                )
            planner = LocalPlanner(
                self._catalogs,
                batch_rows=spec.batch_rows,
                target_splits=spec.target_splits,
                remote_schemas=spec.remote_schemas,
                scan_slice=spec.scan_slice,
                dynamic_filtering=spec.dynamic_filtering,
                stabilizer=stabilizer,
            )
            physical = planner.plan(spec.fragment.root)
            self._note_census(stabilizer)
            if self._memory_pool is not None:
                ctx["memory_pool"] = self._memory_pool
            pipelines, chain = physical.instantiate(ctx)
            sink_buffer = self.buffer
            if self._injector is not None:
                sink_buffer = _MidFailureBuffer(
                    self.buffer, self._injector, spec.task_id
                )
            if spec.record_output:
                from trino_tpu.recovery import RECORDER

                # the tee wraps OUTSIDE the injector proxy so an
                # injected mid-stream kill leaves the recording
                # incomplete, exactly like a real crash would
                sink_buffer = RECORDER.recording_buffer(
                    sink_buffer,
                    spec.task_id.query_id,
                    spec.task_id.fragment_id,
                    str(spec.task_id),
                )
            chain.append(
                PartitionedOutputOperator(
                    sink_buffer,
                    spec.fragment.output_kind,
                    spec.fragment.output_channels,
                    spec.n_output_partitions,
                )
            )
            # instrumentation is ALWAYS on: wall/batch counts, the
            # operator-internal heartbeat, and the shape ledger are
            # cheap (no device sync). Row counting (count_rows) forces a
            # per-batch host sync, so it stays gated on collect_stats —
            # EXPLAIN ANALYZE and query_trace=on set it, and the traced-
            # off arm of the overhead gate is an honest baseline.
            from trino_tpu.exec.stats import instrument

            span_factory = None
            if self._trace is not None:
                parent_id = spec.trace_ctx.get("span_id")
                from trino_tpu.runtime.tracing import KIND_OPERATOR

                def span_factory(op_name, _pid=parent_id):
                    return self._trace.span(
                        op_name, KIND_OPERATOR, parent=_pid,
                        task=str(spec.task_id),
                    )

            def _wrap(ops):
                wrapped, stats = instrument(
                    ops,
                    count_rows=spec.collect_stats,
                    shape_ledger=self._shape_ledger,
                    heartbeat=self.heartbeat,
                    span_factory=span_factory,
                )
                self._instrumented.extend(wrapped)
                return wrapped, stats

            stat_groups = []
            for p in pipelines:
                p.operators, stats = _wrap(p.operators)
                stat_groups.append(stats)
            chain, stats = _wrap(chain)
            stat_groups.append(stats)
            self._stat_groups = stat_groups
            self._run_pipelines(pipelines, chain, spec.task_concurrency)
            for op in self._instrumented:
                op.close_span()
            from trino_tpu.engine import _raise_deferred_checks

            _raise_deferred_checks(ctx)
            if self._census_keys:
                # a completed run compiled (or reused) every class it
                # touched — re-attempts of this fragment shape are warm
                from trino_tpu.compile.warmup import note_classes_warm

                note_classes_warm(self._census_keys)
            self.state = "finished"
        except BaseException as e:
            # full traceback, not just the message: TaskInfo failures
            # travel to the coordinator and are the only evidence a
            # remote crash leaves behind (TaskStatus.getFailures). An
            # externally-killed task already carries its verdict (the
            # low-memory killer's message) — don't overwrite it with the
            # TaskAbortedError unwind.
            if self.failure is None:
                self.failure = "".join(
                    traceback.format_exception(type(e), e, e.__traceback__)
                ).strip()
            self.state = "failed"
            self.buffer.abort()
        finally:
            set_compile_attribution(prev_attr)
            self.end_time = time.time()
            if self._trace is not None:
                # a failed/killed task still exports a fully-closed
                # span set (the invariant checker rejects open spans)
                self._trace.end_open_spans()
            # release every operator reservation: on a SHARED worker
            # pool a failed/killed task would otherwise leak its bytes
            # and poison the pool for every later query
            for mc in ctx.get("memory_contexts", ()):
                try:
                    mc.close()
                except Exception:
                    pass
            for c in self._clients:
                c.close()

    def _note_census(self, stabilizer) -> None:
        """Predict this fragment's shape classes and check them against
        the process-wide warm registry. Best-effort: a census failure
        (exotic plan shape, missing stats) just leaves shapes_warm
        False, which keeps the conservative watchdog threshold."""
        try:
            from trino_tpu.compile.warmup import classes_warm
            from trino_tpu.sql.validate import shape_census

            census = shape_census(
                self.spec.fragment.root,
                self._catalogs,
                batch_rows=self.spec.batch_rows,
                dynamic_filtering=self.spec.dynamic_filtering,
                ladder=stabilizer.ladder if stabilizer is not None else None,
            )
            self._census_keys = frozenset(
                (c.operator, c.capacity, c.dtypes) for c in census
            )
            self.shapes_warm = classes_warm(self._census_keys)
        except Exception:
            self._census_keys = frozenset()
            self.shapes_warm = False

    def _run_pipelines(self, pipelines, chain, concurrency: int) -> None:
        """Drive the task's pipelines. concurrency > 1 enables the
        intra-task parallel form (LocalExchange.java:67 discipline): a
        chain headed by a remote source splits at a LocalExchange so
        page pulls + deserialization (host) overlap the device compute
        downstream. Build pipelines run sequentially in planner order —
        they can be DEPENDENT (a join-on-join build side embeds the
        inner join's probe; see _visit_JoinNode), so concurrent starts
        need a bridge-readiness protocol the operators don't have."""
        from trino_tpu.exec.exchange_ops import RemoteSourceOperator
        from trino_tpu.exec.local_exchange import (
            LocalExchange,
            LocalExchangeSinkOperator,
            LocalExchangeSourceOperator,
        )

        def stop() -> bool:
            # fail_query / abort flip the state machine externally; the
            # driver polls it at batch boundaries so a killed task stops
            # instead of grinding through grace-join spill work
            return self._state_machine.get() in ("aborted", "failed")

        def drive(p):
            Driver(p, should_stop=stop, observer=self._on_batch).run()

        # build pipelines run SEQUENTIALLY: the local planner emits them
        # in dependency order (a join-on-join build side embeds the
        # inner join's probe, which reads the inner build's bridge —
        # concurrent starts would probe an unfinished lookup source)
        for p in pipelines:
            drive(p)
        head = chain[0] if chain else None
        # the head is wrapped by InstrumentedOperator — the concurrency
        # split keys on the REAL operator underneath
        head_inner = getattr(head, "inner", head)
        if (
            concurrency > 1
            and len(chain) > 1
            and isinstance(head_inner, RemoteSourceOperator)
        ):
            # overlap remote-page pulls/deserialization with the device
            # compute downstream (the LocalExchange split)
            ex = LocalExchange(n_consumers=1, mode="arbitrary")
            producer = Pipeline([head, LocalExchangeSinkOperator(ex)])
            consumer = Pipeline(
                [LocalExchangeSourceOperator(ex)] + list(chain[1:])
            )
            perr: List[BaseException] = []

            def run_producer():
                # compiles attribute to the dispatching thread — the
                # producer thread needs the task's query id too
                from trino_tpu.runtime.metrics import set_compile_attribution

                set_compile_attribution(self.spec.task_id.query_id)
                try:
                    drive(producer)
                except BaseException as e:
                    perr.append(e)
                    # unblock the consumer by FAILING the exchange, not
                    # finishing it: a clean producer_finished() here
                    # would let the consumer treat the truncated stream
                    # as end-of-input and publish an empty 'complete'
                    # result while the upstream failure is still in
                    # flight (the killed-query-returns-empty race)
                    ex.producer_failed(e)

            t = threadreg.spawn("pipeline-producer", run_producer,
                                owner="TaskExecution")
            try:
                drive(consumer)
            except BaseException:
                # a failed consumer must not abandon the producer
                # blocked in put(): abort drops buffered pages and
                # makes further puts no-ops
                ex.abort()
                t.join(5)
                if perr:
                    # the producer died first — its error is the root
                    # cause; the consumer unwind is secondary noise
                    raise perr[0]
                raise
            t.join()
            if perr:
                raise perr[0]
        else:
            drive(Pipeline(chain))
