"""Stage-planning helpers shared by the pipelined and FTE schedulers:
topological fragment order, task-count policy, and the coordinator-side
schema-propagation pass (StageManager/DeterminePartitionCount-adjacent
logic that must not diverge between scheduling modes)."""

from __future__ import annotations

from typing import Dict, List

from trino_tpu.sql.fragmenter import SubPlan


def topo_order(subplan: SubPlan) -> List[SubPlan]:
    """Children before parents (producers schedule before consumers)."""
    out: List[SubPlan] = []

    def walk(sp: SubPlan) -> None:
        for c in sp.children:
            walk(c)
        out.append(sp)

    walk(subplan)
    return out


def stage_task_count(sp: SubPlan, n_workers: int, hash_partitions: int) -> int:
    """Task-count policy per fragment partitioning; hash stages take the
    stats-driven suggestion (DeterminePartitionCount.java:90) capped by
    the session's hash_partition_count."""
    p = sp.fragment.partitioning
    if p == "single":
        return 1
    if p == "source":
        return max(1, n_workers)
    suggested = sp.fragment.suggested_partitions
    if suggested is not None:
        return max(1, min(hash_partitions, suggested))
    return hash_partitions


def fragment_schema(catalogs, session, sp: SubPlan, remote: Dict[int, list]) -> list:
    """Coordinator-side planning pass for a fragment's output schema
    (dictionaries included) so consumer fragments can bind expressions."""
    from trino_tpu.sql.local_planner import LocalPlanner

    planner = LocalPlanner(
        catalogs,
        batch_rows=session.batch_rows,
        remote_schemas=remote,
    )
    return planner.plan(sp.fragment.root).schema
