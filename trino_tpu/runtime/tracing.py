"""Query tracing: explicit-parent spans, cluster-wide, Perfetto-exportable.

Analogue of the reference's OpenTelemetry integration (TracingMetadata,
ScopedSpan, spans per planning phase in SqlQueryExecution, and the
W3C-traceparent propagation coordinator->worker via TaskResource —
SURVEY.md §5.1), reduced to an in-process recorder with the same tree
shape and propagation discipline:

- NO globals and NO thread-local ambient context: a span is created from
  an explicit parent handle (``parent.child(...)`` or
  ``trace.span(..., parent=...)``), so spans opened on scheduler poll
  threads, FTE retry loops, and worker pipelines land under the right
  parent regardless of which thread touches them.
- Span context crosses the coordinator->worker boundary as plain data
  (``wire_context(span)`` -> dict on ``TaskSpec.trace_ctx``); the worker
  records its operator spans against the remote parent id and ships them
  back flat in task status, where ``QueryTrace.graft`` re-attaches them.
- Export is a flat OTel-style span list (``export()``) plus a Chrome
  trace-event rendering (``chrome_trace``) loadable in Perfetto /
  chrome://tracing; annotations (retry, speculation, drain, deadline,
  watchdog, chaos faults) become instant events on the owning span's
  track so a chaos run reads as one timeline.

Span kinds form the tree contract the invariant checker enforces:
``query`` roots the trace; ``phase`` (parse/analyze/optimize/validate/
fragment/schedule) and ``stage`` spans hang off it; ``task`` spans hang
off stages (one per attempt); ``operator`` spans hang off tasks.
"""

from __future__ import annotations

import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import time
import uuid
from typing import Any, Dict, List, Optional, Union

# span kinds, in tree order (parent kind of each child kind)
KIND_QUERY = "query"
KIND_PHASE = "phase"
KIND_STAGE = "stage"
KIND_TASK = "task"
KIND_OPERATOR = "operator"

_PARENT_KIND = {
    KIND_PHASE: KIND_QUERY,
    KIND_STAGE: KIND_QUERY,
    KIND_TASK: KIND_STAGE,
    KIND_OPERATOR: KIND_TASK,
}


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One timed node. Created via QueryTrace.span / Span.child only;
    the explicit parent handle IS the propagation mechanism."""

    __slots__ = (
        "name", "kind", "span_id", "trace_id", "parent_id",
        "start_s", "end_s", "attributes", "events", "_trace",
    )

    def __init__(self, trace: "QueryTrace", name: str, kind: str,
                 parent_id: Optional[str], **attributes):
        self.name = name
        self.kind = kind
        self.span_id = _new_id()
        self.trace_id = trace.trace_id
        self.parent_id = parent_id
        self.start_s = time.time()
        self.end_s: Optional[float] = None
        self.attributes: Dict[str, Any] = dict(attributes)
        self.events: List[dict] = []
        self._trace = trace

    def child(self, name: str, kind: str, **attributes) -> "Span":
        return self._trace.span(name, kind, parent=self, **attributes)

    def event(self, name: str, **attributes) -> None:
        """Timestamped annotation on this span (otel addEvent)."""
        self.events.append({
            "ts": time.time(), "name": name,
            "attributes": dict(attributes),
        })

    def set(self, **attributes) -> None:
        self.attributes.update(attributes)

    def end(self, end_s: Optional[float] = None) -> None:
        if self.end_s is None:
            self.end_s = time.time() if end_s is None else end_s

    @property
    def ended(self) -> bool:
        return self.end_s is not None

    @property
    def duration_s(self) -> float:
        return (self.end_s or time.time()) - self.start_s

    # `with parent.child("analyze", KIND_PHASE):` — exceptions annotate
    # the span and it still closes, so no failure path leaks open spans
    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None and not self.ended:
            self.event("exception", type=type(exc).__name__,
                       message=str(exc)[:500])
            self.attributes.setdefault("error", True)
        self.end()

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "span_id": self.span_id,
            "trace_id": self.trace_id,
            "parent_id": self.parent_id,
            "start_s": self.start_s,
            "end_s": self.end_s,
            "duration_ms": round(self.duration_s * 1000, 3),
            "attributes": dict(self.attributes),
            "events": [dict(e) for e in self.events],
        }


def wire_context(span: Span) -> dict:
    """Plain-data span context for TaskSpec (traceparent analogue).
    Strings only, so the wire codec ships it with no schema change."""
    return {"trace_id": span.trace_id, "span_id": span.span_id}


class QueryTrace:
    """All spans of one query. Coordinator-side it holds the full tree;
    worker-side (``QueryTrace.remote``) it holds only the spans recorded
    in that process, parented on the remote context, for export back."""

    def __init__(self, query_id: str, trace_id: Optional[str] = None):
        self.query_id = query_id
        self.trace_id = trace_id or _new_id()
        self._lock = named_lock("QueryTrace._lock")
        self._spans: List[Span] = []
        self._grafted: List[dict] = []

    @classmethod
    def remote(cls, ctx: dict, query_id: str = "") -> "QueryTrace":
        """Worker-side recorder attached to a coordinator's context."""
        return cls(query_id, trace_id=ctx.get("trace_id"))

    def span(self, name: str, kind: str,
             parent: Union[Span, str, None] = None, **attributes) -> Span:
        pid = parent.span_id if isinstance(parent, Span) else parent
        s = Span(self, name, kind, pid, **attributes)
        with self._lock:
            self._spans.append(s)
        return s

    def graft(self, span_dicts: List[dict]) -> int:
        """Attach already-exported foreign spans (a worker's operator
        spans) into this trace. They carry their own parent ids — the
        coordinator handed those ids out via wire_context, so the tree
        closes. Duplicate span_ids (a task polled twice) are dropped."""
        with self._lock:
            seen = {s.span_id for s in self._spans}
            seen.update(d.get("span_id") for d in self._grafted)
            added = 0
            for d in span_dicts or []:
                if d.get("span_id") in seen:
                    continue
                seen.add(d.get("span_id"))
                d = dict(d)
                d["trace_id"] = self.trace_id
                self._grafted.append(d)
                added += 1
            return added

    def end_open_spans(self, end_s: Optional[float] = None) -> int:
        """Close every still-open span (abnormal-completion sweep so a
        failed/killed query still exports a fully-closed tree). Grafted
        worker spans are swept too: a task killed mid-stall ships its
        spans before its driver thread's own finally can close them."""
        n = 0
        stamp = time.time() if end_s is None else end_s
        with self._lock:
            spans = list(self._spans)
            for d in self._grafted:
                if d.get("end_s") is None:
                    d["end_s"] = max(stamp, d.get("start_s") or stamp)
                    d["duration_ms"] = round(
                        (d["end_s"] - (d.get("start_s") or d["end_s"]))
                        * 1000, 3,
                    )
                    n += 1
        for s in spans:
            if not s.ended:
                s.end(end_s)
                n += 1
        return n

    def export(self) -> dict:
        with self._lock:
            dicts = [s.to_dict() for s in self._spans]
            dicts += [dict(d) for d in self._grafted]
        dicts.sort(key=lambda d: (d.get("start_s") or 0.0))
        return {
            "trace_id": self.trace_id,
            "query_id": self.query_id,
            "spans": dicts,
        }


# -- exports ------------------------------------------------------------


def chrome_trace(export: dict) -> List[dict]:
    """Render a QueryTrace.export() as Chrome trace-event JSON (the
    `traceEvents` list — load in Perfetto or chrome://tracing).

    Complete events (ph "X") carry each span; span annotations become
    instant events (ph "i") on the same track. Track (tid) assignment
    keeps the rendering readable: coordinator work (query + phases) on
    tid 0, each stage on its own track, each task attempt (plus its
    operator spans) on its own track — parallel attempts never overlap
    on one row, which "X" nesting cannot express."""
    spans = export.get("spans", [])
    if not spans:
        return []
    t0 = min(s.get("start_s") or 0.0 for s in spans)
    by_id = {s["span_id"]: s for s in spans}
    tids: Dict[str, int] = {}
    names: Dict[int, str] = {0: "coordinator"}
    next_tid = [1]

    def tid_of(span: dict) -> int:
        sid = span["span_id"]
        if sid in tids:
            return tids[sid]
        if span.get("kind") in (KIND_STAGE, KIND_TASK):
            t = next_tid[0]
            next_tid[0] += 1
            names[t] = span.get("name", span.get("kind"))
        else:
            parent = by_id.get(span.get("parent_id") or "")
            t = tid_of(parent) if parent is not None else 0
        tids[sid] = t
        return t

    events: List[dict] = []
    for s in spans:
        tid = tid_of(s)
        start = s.get("start_s") or t0
        end = s.get("end_s") or start
        events.append({
            "name": s.get("name", "?"),
            "cat": s.get("kind", "span"),
            "ph": "X",
            "ts": round((start - t0) * 1e6, 1),
            "dur": round(max(0.0, end - start) * 1e6, 1),
            "pid": 1,
            "tid": tid,
            "args": dict(s.get("attributes") or {},
                         span_id=s["span_id"]),
        })
        for ev in s.get("events") or []:
            events.append({
                "name": ev.get("name", "event"),
                "cat": "annotation",
                "ph": "i",
                "s": "t",
                "ts": round(((ev.get("ts") or start) - t0) * 1e6, 1),
                "pid": 1,
                "tid": tid,
                "args": dict(ev.get("attributes") or {}),
            })
    meta = [
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": t,
         "args": {"name": n}}
        for t, n in sorted(names.items())
    ]
    return meta + events


def check_span_invariants(export: dict) -> List[str]:
    """Structural invariants on an exported trace; returns violations
    (empty == healthy). Enforced by tests and `bench.py --trace-smoke`:

    - exactly one root, and it is the query span
    - every non-root parent_id resolves to a span in the trace
    - kind hierarchy holds: phase/stage under query, task under stage,
      operator under task
    - no span is left open (end_s set, end >= start)
    """
    spans = export.get("spans", [])
    violations: List[str] = []
    by_id = {s["span_id"]: s for s in spans}
    roots = [s for s in spans if not s.get("parent_id")]
    if len(roots) != 1:
        violations.append(
            f"expected exactly 1 root span, found {len(roots)}: "
            f"{[r.get('name') for r in roots]}"
        )
    for r in roots:
        if r.get("kind") != KIND_QUERY:
            violations.append(
                f"root span {r.get('name')!r} has kind "
                f"{r.get('kind')!r}, expected {KIND_QUERY!r}"
            )
    for s in spans:
        label = f"{s.get('kind')}:{s.get('name')}({s['span_id']})"
        pid = s.get("parent_id")
        parent = by_id.get(pid) if pid else None
        if pid and parent is None:
            violations.append(f"orphan span {label}: parent {pid} "
                              f"not in trace")
        want = _PARENT_KIND.get(s.get("kind"))
        if want is not None and parent is not None \
                and parent.get("kind") != want:
            violations.append(
                f"span {label} parented on kind "
                f"{parent.get('kind')!r}, expected {want!r}"
            )
        if s.get("end_s") is None:
            violations.append(f"unclosed span {label}")
        elif s.get("start_s") is not None \
                and s["end_s"] < s["start_s"] - 1e-6:
            violations.append(f"span {label} ends before it starts")
    return violations
