"""Event listener SPI.

Analogue of spi/eventlistener/EventListener.java:16 (queryCreated /
queryCompleted / splitCompleted; plugins like trino-http-event-listener
— SURVEY.md §5.5). Listeners are registered on the engine/coordinator;
failures in listeners never fail queries (dispatch swallows + records)."""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class QueryCreatedEvent:
    query_id: str
    sql: str
    create_time: float


@dataclasses.dataclass
class QueryCompletedEvent:
    query_id: str
    sql: str
    state: str  # finished | failed
    wall_s: float
    rows: int = 0
    failure: Optional[str] = None


@dataclasses.dataclass
class SplitCompletedEvent:
    query_id: str
    task_id: str
    wall_s: float


class EventListener:
    """Subclass and override; unimplemented events are ignored."""

    def query_created(self, event: QueryCreatedEvent) -> None:
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass

    def split_completed(self, event: SplitCompletedEvent) -> None:
        pass


class EventListenerManager:
    def __init__(self):
        self._listeners: List[EventListener] = []
        self.dispatch_failures = 0

    def add(self, listener: EventListener) -> None:
        self._listeners.append(listener)

    def _fire(self, method: str, event) -> None:
        for lst in self._listeners:
            try:
                getattr(lst, method)(event)
            except Exception:
                self.dispatch_failures += 1

    def query_created(self, event: QueryCreatedEvent) -> None:
        self._fire("query_created", event)

    def query_completed(self, event: QueryCompletedEvent) -> None:
        self._fire("query_completed", event)

    def split_completed(self, event: SplitCompletedEvent) -> None:
        self._fire("split_completed", event)
