"""Event listener SPI.

Analogue of spi/eventlistener/EventListener.java:16 (queryCreated /
queryCompleted / splitCompleted; plugins like trino-http-event-listener
— SURVEY.md §5.5). Listeners are registered on the engine/coordinator;
failures in listeners never fail queries (dispatch swallows + records)."""

from __future__ import annotations

import dataclasses
import json
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import time
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class QueryCreatedEvent:
    query_id: str
    sql: str
    create_time: float


@dataclasses.dataclass
class QueryCompletedEvent:
    """Completion record (QueryCompletedEvent.java's QueryStatistics/
    QueryFailureInfo payload, flattened). The resource fields default to
    zero so cheap paths can fire a minimal event; the coordinator and
    engine fill them from the final QueryInfo."""

    query_id: str
    sql: str
    state: str  # finished | failed
    wall_s: float
    rows: int = 0
    failure: Optional[str] = None
    # -- QueryStatistics analogue --
    peak_memory_bytes: int = 0
    rows_scanned: int = 0
    bytes_scanned: int = 0
    rows_shuffled: int = 0
    compile_count: int = 0
    cpu_s: float = 0.0
    # -- QueryFailureInfo / retry accounting --
    error_code: Optional[str] = None  # EXCEEDED_*_LIMIT etc.
    retry_count: int = 0   # query-level resubmissions
    attempt_count: int = 1  # task attempts launched (FTE), else 1


@dataclasses.dataclass
class SplitCompletedEvent:
    query_id: str
    task_id: str
    wall_s: float


class EventListener:
    """Subclass and override; unimplemented events are ignored."""

    def query_created(self, event: QueryCreatedEvent) -> None:
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass

    def split_completed(self, event: SplitCompletedEvent) -> None:
        pass


class JsonlEventListener(EventListener):
    """Append one JSON line per completed query to `path` — the
    http-event-listener analogue with a file sink instead of a POST.
    Line schema is the QueryCompletedEvent field set plus `event` and
    `emit_time`; writes are locked so concurrent completions from the
    server's submit threads never interleave."""

    def __init__(self, path: str):
        self.path = path
        self._lock = named_lock("JsonlEventListener._lock")

    def query_completed(self, event: QueryCompletedEvent) -> None:
        record: Dict[str, Any] = {"event": "query_completed",
                                  "emit_time": time.time()}
        record.update(dataclasses.asdict(event))
        line = json.dumps(record, default=str)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")


class EventListenerManager:
    def __init__(self):
        self._listeners: List[EventListener] = []
        self.dispatch_failures = 0

    def add(self, listener: EventListener) -> None:
        self._listeners.append(listener)

    def register_metrics(
        self, name: str = "event_listener_dispatch_failures"
    ) -> None:
        """Expose dispatch_failures as a gauge on the process metrics
        registry (swallowed listener exceptions are otherwise
        invisible)."""
        from trino_tpu.runtime.metrics import METRICS

        METRICS.register_gauge(name, lambda: self.dispatch_failures)

    def _fire(self, method: str, event) -> None:
        for lst in self._listeners:
            try:
                getattr(lst, method)(event)
            except Exception:
                self.dispatch_failures += 1

    def query_created(self, event: QueryCreatedEvent) -> None:
        self._fire("query_created", event)

    def query_completed(self, event: QueryCompletedEvent) -> None:
        self._fire("query_completed", event)

    def split_completed(self, event: SplitCompletedEvent) -> None:
        self._fire("split_completed", event)
